"""Content-addressed pull-on-demand blob plane — one per TransportManager.

The repo's transport was purely push-based: the data owner initiates
every transfer, so every large immutable object (base weights, join
welcomes, checkpoint restores) was eagerly shipped even when the
receiver already held the bytes.  The :class:`ObjectPlane` grows the
rendezvous mailbox into a content-addressed blob layer and introduces
the repo's FIRST pull direction:

- **fingerprint handles** — the owner serializes once, fingerprints the
  wire bytes (``wire.blob_fingerprint``, built on the delta-cache's
  chunk-CRC machinery) and passes a small handle instead of the payload
  (:mod:`rayfed_tpu.objects` owns the schemas);
- **BLOB_GET / BLOB_PUT** — a request/reply pair riding the EXISTING
  frame machinery: the request is a tiny payload-less frame stamped
  with ``wire.BLOB_GET_KEY`` metadata (consumed by a server observer,
  like roster membership requests); the reply is an ordinary DATA push
  of the stored wire bytes onto the reply rendezvous key the requester
  is already parked on — so per-chunk CRCs, multi-rail striping and
  stripe reassembly all apply unchanged, with **no new socket**;
- **bounded content-addressed LRU** — byte-budget eviction with
  pin/unpin for live round state, concurrent-fetch dedup (N waiters on
  one fingerprint trigger ONE transfer), and verify-on-arrival: a
  corrupt blob is dropped LOUDLY and re-fetched from a different
  holder;
- **dead-holder failover** — the pull parks in the mailbox with the
  holder named (``Mailbox.get``'s ``src_party``), so a pull aimed at a
  monitor-declared-dead holder fails IMMEDIATELY (the mirror of the
  PR 3 chunk-sink registration fix) and fails over to the next named
  holder instead of waiting out the recv backstop; a holder that does
  not hold the bytes replies a payload-less miss notice with the same
  effect.

What stays push-based: per-round contributions and aggregates (fresh
content every round — nothing to deduplicate), control traffic, and
anything below the handle-offer size floor.  See
``docs/source/object_plane.rst``.
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import threading
import time
import uuid
from typing import Any, Dict, Optional, Sequence

from rayfed_tpu import objects, telemetry
from rayfed_tpu.objects import ObjectPlaneError
from rayfed_tpu.transport import wire

logger = logging.getLogger(__name__)

# Rendezvous-key prefixes of the pull protocol.  Requests are consumed
# by a server observer (never enter the mailbox); replies land on a
# per-pull nonce key the requester parks on — derived, not drawn from
# the global seq counter, so pulls compose with rejoin (nothing to
# reconstruct) and two concurrent pulls can never collide.
BLOB_REQ_PREFIX = "blob.req."
BLOB_REPLY_PREFIX = "blob.put."
_BLOB_DOWN = "blob"

# Default byte budget of the content-addressed cache.  Pinned entries
# (live round state: the current model, a just-offered broadcast) are
# never evicted and may exceed the budget; unpinned entries are evicted
# LRU-first the moment the total crosses it.
DEFAULT_BLOB_CACHE_BUDGET = 256 << 20


class _HolderFailure(Exception):
    """One holder could not produce the blob; the pull fails over."""

    def __init__(self, kind: str, detail: str) -> None:
        super().__init__(detail)
        self.kind = kind  # "dead" | "miss" | "corrupt" | "timeout" | "send"


class _Entry:
    __slots__ = ("data", "pinned")

    def __init__(self, data: bytes, pinned: bool) -> None:
        self.data = data
        self.pinned = pinned


class BlobStore:
    """Bounded content-addressed LRU: fingerprint → immutable bytes.

    Thread-safe (hit from user threads, the codec pool, and the
    transport loop's observer).  ``pin``/``unpin`` protect live round
    state from byte-budget eviction; pinned bytes do not count against
    the budget the way candidates do — they simply never leave.
    """

    def __init__(self, budget_bytes: int = DEFAULT_BLOB_CACHE_BUDGET) -> None:
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[str, _Entry]" = (
            collections.OrderedDict()
        )
        self._bytes = 0
        self.budget_bytes = int(budget_bytes)
        self.stats: Dict[str, int] = {
            "blob_store_puts": 0,
            "blob_store_evictions": 0,
            "blob_store_evicted_bytes": 0,
        }

    def put(self, fp: str, data: bytes, pin: bool = False) -> None:
        data = bytes(data)
        with self._lock:
            entry = self._entries.get(fp)
            if entry is not None:
                # Same content (content-addressed): refresh recency and
                # possibly strengthen the pin; never duplicate bytes.
                self._entries.move_to_end(fp)
                entry.pinned = entry.pinned or pin
                return
            self._entries[fp] = _Entry(data, pin)
            self._bytes += len(data)
            self.stats["blob_store_puts"] += 1
            self._evict_locked()

    def _evict_locked(self) -> None:
        if self._bytes <= self.budget_bytes:
            return
        # Never evict the most recently touched entry: the blob just
        # stored/served IS the working set, even when pinned entries
        # alone exceed the budget.
        for fp in list(self._entries)[:-1]:
            if self._bytes <= self.budget_bytes:
                break
            entry = self._entries[fp]
            if entry.pinned:
                continue
            del self._entries[fp]
            self._bytes -= len(entry.data)
            self.stats["blob_store_evictions"] += 1
            self.stats["blob_store_evicted_bytes"] += len(entry.data)

    def get(self, fp: str) -> Optional[bytes]:
        with self._lock:
            entry = self._entries.get(fp)
            if entry is None:
                return None
            self._entries.move_to_end(fp)
            return entry.data

    def contains(self, fp: str) -> bool:
        with self._lock:
            return fp in self._entries

    def pin(self, fp: str) -> None:
        with self._lock:
            entry = self._entries.get(fp)
            if entry is None:
                raise KeyError(f"cannot pin unknown blob {fp}")
            entry.pinned = True

    def unpin(self, fp: str) -> None:
        """Release a pin; the entry stays cached but becomes evictable
        (and is evicted right away when the store is over budget)."""
        with self._lock:
            entry = self._entries.get(fp)
            if entry is None:
                return
            entry.pinned = False
            self._evict_locked()

    def total_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def pinned_bytes(self) -> int:
        with self._lock:
            return sum(
                len(e.data) for e in self._entries.values() if e.pinned
            )

    def fingerprints(self) -> list:
        with self._lock:
            return list(self._entries)


class ObjectPlane:
    """The per-TransportManager pull-on-demand plane (module docstring).

    Construction wires a server observer that consumes BLOB_GET request
    frames (loop thread) and serves them off-loop from the store; pulls
    run on the caller's thread, parking in the mailbox exactly like an
    ordinary recv — dead-party fast-fail included.
    """

    def __init__(
        self, manager, budget_bytes: int = DEFAULT_BLOB_CACHE_BUDGET
    ) -> None:
        self._manager = manager
        self.store = BlobStore(budget_bytes)
        self._lock = threading.Lock()
        self._fetch_pool = None  # lazy; see fetch_executor
        # fingerprint → Future shared by every concurrent local fetch of
        # the same content: N waiters, ONE transfer.
        self._inflight: Dict[str, Any] = {}
        # named pin slots (e.g. the quorum loop's current round model):
        # publishing a new generation into a slot unpins the previous.
        self._slots: Dict[str, str] = {}
        self.stats: Dict[str, int] = {
            "blob_cache_hits": 0,
            "blob_cache_misses": 0,
            "blob_fetches": 0,
            "blob_fetch_bytes": 0,
            "blob_dedup_waits": 0,
            "blob_corrupt_refetches": 0,
            "blob_dead_holder_failovers": 0,
            "blob_serves": 0,
            "blob_serve_bytes": 0,
            "blob_serve_misses": 0,
            "blob_offers": 0,
        }

    @property
    def party(self) -> str:
        return self._manager._party

    @property
    def fetch_executor(self):
        """A small dedicated pool for blocking handle resolution.

        A pull parks for up to a holder round trip — running it on the
        manager's shared codec pool would starve encode/decode and,
        worse, the BLOB_GET *serves* of symmetric pulls (two parties
        each pulling from the other could wedge until timeout).  The
        ``fed.get`` receive chain resolves handles HERE instead; the
        codec pool stays free for quick work."""
        import concurrent.futures as _futures

        with self._lock:
            if self._fetch_pool is None:
                self._fetch_pool = _futures.ThreadPoolExecutor(
                    max_workers=4,
                    thread_name_prefix=f"rayfed-blob-{self.party}",
                )
            return self._fetch_pool

    # -- publish (owner side) ---------------------------------------------

    def publish(
        self, value: Any = None, *, data: Optional[bytes] = None,
        pin: bool = False,
    ) -> tuple:
        """Store one object's wire bytes content-addressed; returns
        ``(fingerprint, nbytes)``.  Pass ``data=`` when the serialized
        bytes already exist (e.g. a just-received payload)."""
        if data is None:
            fp, data = objects.fingerprint_value(value)
        else:
            data = bytes(data)
            fp = wire.blob_fingerprint(data)
        self.store.put(fp, data, pin=pin)
        return fp, len(data)

    def publish_slot(self, slot: str, value: Any = None, *,
                     data: Optional[bytes] = None) -> tuple:
        """Publish pinned into a named slot, unpinning the slot's
        previous generation — how the quorum loop keeps exactly the
        CURRENT round model protected from eviction.  Slot bookkeeping
        is under the plane lock: two racing publishes into one slot
        must leave exactly ONE pinned winner (an orphaned pin would be
        a permanent cache leak)."""
        fp, n = self.publish(value, data=data, pin=True)
        with self._lock:
            prev = self._slots.get(slot)
            self._slots[slot] = fp
        if prev is not None and prev != fp:
            self.store.unpin(prev)
        return fp, n

    def handle_for(
        self, fp: str, nbytes: int, extra_holders: Sequence[str] = ()
    ) -> Dict[str, Any]:
        """A handle naming this party (the publisher) as first holder."""
        holders = [self.party] + [
            h for h in extra_holders if h != self.party
        ]
        return objects.make_blob_handle(fp, nbytes, holders)

    def maybe_offer(self, value: Any, min_bytes: Optional[int]):
        """The ``fed.get`` broadcast hook: when ``value`` is a large
        immutable object (a plain :class:`~rayfed_tpu.fl.compression.
        PackedTree` at or above the size floor), publish it and return
        the handle to send IN PLACE of the payload; otherwise ``None``
        (the eager push proceeds unchanged).  Only exact PackedTrees
        are offered: quantized/masked subclasses carry round-scoped
        grid/mask state that is not content-stable across receivers.
        """
        if min_bytes is None or min_bytes <= 0:
            return None
        from rayfed_tpu.fl.compression import PackedTree

        if type(value) is not PackedTree:
            return None
        try:
            nb = int(getattr(value.buf, "nbytes", 0))
        except Exception:  # pragma: no cover - exotic buf
            return None
        if nb < int(min_bytes):
            return None
        # Slot-pinned: the LATEST offer stays eviction-proof while
        # receivers pull; earlier offers become ordinary LRU citizens
        # (still served on a hit, evicted only under byte pressure).
        fp, n = self.publish_slot("offer", value)
        self.stats["blob_offers"] += 1
        return self.handle_for(fp, n)

    # -- fetch (puller side) ----------------------------------------------

    def fetch_local_bytes(self, fp: str) -> Optional[bytes]:
        """The stored wire bytes for ``fp`` — local cache only, no pull
        (checkpoint restore resolves by fingerprint BEFORE touching
        disk through exactly this)."""
        return self.store.get(fp)

    def fetch(
        self, handle: Dict[str, Any], timeout_s: Optional[float] = None,
        decode: bool = True,
    ) -> Any:
        """Resolve a handle: content-cache hit → zero wire bytes; miss
        → ONE pull shared by every concurrent local waiter, tried
        against the named holders in order with dead/miss/corrupt
        failover.  ``decode=False`` returns the raw wire bytes."""
        handle = objects.check_blob_handle(handle)
        fp = handle["fp"]
        data = self.store.get(fp)
        if data is not None:
            self.stats["blob_cache_hits"] += 1
            # Flight recorder: one record per resolve with its pull
            # temperature (warm hit / dedup ride / cold wire pull) —
            # the "did the handle actually save bytes" question, per
            # pull instead of summed in stats_snapshot.  Guarded: warm
            # hits are the hot resolve path, so disarmed cost stays
            # one global read (no argument construction).
            if telemetry.active() is not None:
                telemetry.event(
                    "blob.fetch", party=self.party, nbytes=len(data),
                    outcome="warm", detail={"fp": fp},
                )
            return self._decode(data) if decode else data
        self.stats["blob_cache_misses"] += 1
        import concurrent.futures as _futures

        backstop = (
            float(timeout_s) if timeout_s is not None
            else float(self._manager._job.recv_backstop_s)
        )
        with self._lock:
            fut = self._inflight.get(fp)
            owner = fut is None
            if owner:
                fut = _futures.Future()
                self._inflight[fp] = fut
        if not owner:
            # Concurrent-fetch dedup: ride the in-flight transfer.  The
            # owner may legitimately spend up to one backstop PER named
            # holder (failover), so the waiter bound scales with the
            # holder count — and a waiter timeout surfaces as the
            # plane's own loud error type, never a bare futures
            # TimeoutError.
            self.stats["blob_dedup_waits"] += 1
            t0_wall, t0 = time.time(), time.perf_counter()
            try:
                data = fut.result(
                    timeout=backstop * max(1, len(handle["holders"])) + 5
                )
            except _futures.TimeoutError:
                raise ObjectPlaneError(
                    f"blob {fp}: the in-flight pull this fetch was "
                    f"riding did not finish within the holder-failover "
                    f"window"
                ) from None
            telemetry.emit(
                "blob.fetch", party=self.party, nbytes=len(data),
                t_start=t0_wall, dur_s=time.perf_counter() - t0,
                outcome="dedup", detail={"fp": fp},
            )
            return self._decode(data) if decode else data
        t0_wall, t0 = time.time(), time.perf_counter()
        try:
            data = self.store.get(fp)  # raced-in between miss and lock
            if data is None:
                data = self._pull(handle, backstop)
                self.store.put(fp, data)
                telemetry.emit(
                    "blob.fetch", party=self.party, nbytes=len(data),
                    t_start=t0_wall, dur_s=time.perf_counter() - t0,
                    outcome="cold", detail={"fp": fp},
                )
            else:
                # A concurrent owner completed between the miss and the
                # inflight lock: a resolve is a resolve — every path
                # leaves a blob.fetch record or temperature counts stop
                # reconciling with stats_snapshot under concurrency.
                telemetry.event(
                    "blob.fetch", party=self.party, nbytes=len(data),
                    outcome="warm", detail={"fp": fp, "raced": True},
                )
            fut.set_result(data)
        except BaseException as exc:
            fut.set_exception(exc)
            raise
        finally:
            with self._lock:
                self._inflight.pop(fp, None)
        return self._decode(data) if decode else data

    def _decode(self, data: bytes) -> Any:
        """Decode exactly like the ordinary recv path, so a handle-
        resolved object is indistinguishable from an eager push."""
        mgr = self._manager
        mesh = mgr.mesh_provider() if mgr.mesh_provider else None
        return objects.deserialize_blob(
            data,
            allowed=mgr._cluster.serializing_allowed_list,
            device_put=mgr._job.device_put_received,
            mesh=mesh,
            zero_copy=mgr._job.zero_copy_host_arrays,
        )

    def _pull(self, handle: Dict[str, Any], timeout_s: float) -> bytes:
        fp = handle["fp"]
        holders = objects.holders_for(handle, exclude=(self.party,))
        if not holders:
            raise ObjectPlaneError(
                f"blob {fp} is not cached locally and the handle names "
                f"no other holder ({handle['holders']})"
            )
        outcomes = []
        for holder in holders:
            try:
                data = self._pull_once(fp, holder, timeout_s)
            except _HolderFailure as exc:
                outcomes.append(f"{holder}: {exc.kind} ({exc})")
                telemetry.event(
                    "blob.failover", party=self.party, peer=holder,
                    outcome=exc.kind, detail={"fp": fp},
                )
                if exc.kind == "corrupt":
                    self.stats["blob_corrupt_refetches"] += 1
                    logger.warning(
                        "[%s] blob %s from holder %s FAILED content "
                        "verification on arrival (%s); re-fetching from "
                        "a different holder",
                        self.party, fp, holder, exc,
                    )
                elif exc.kind == "dead":
                    self.stats["blob_dead_holder_failovers"] += 1
                    logger.warning(
                        "[%s] blob pull of %s: holder %s is declared "
                        "dead; failing over to the next named holder",
                        self.party, fp, holder,
                    )
                else:
                    logger.warning(
                        "[%s] blob pull of %s from %s failed (%s: %s); "
                        "trying the next holder",
                        self.party, fp, holder, exc.kind, exc,
                    )
                continue
            self.stats["blob_fetches"] += 1
            self.stats["blob_fetch_bytes"] += len(data)
            return data
        raise ObjectPlaneError(
            f"blob pull of {fp} failed at every named holder: "
            f"{'; '.join(outcomes)}"
        )

    def _pull_once(self, fp: str, holder: str, timeout_s: float) -> bytes:
        """One BLOB_GET round trip against one holder.

        The reply wait is an ordinary mailbox park WITH the holder
        named (``src_party``): a holder already declared dead fails the
        park immediately, and one that dies mid-pull is failed by the
        health monitor within its death deadline — never the backstop.
        """
        mgr = self._manager
        nonce = uuid.uuid4().hex
        reply_up = f"{BLOB_REPLY_PREFIX}{fp}.{self.party}.{nonce}"
        recv_cf = asyncio.run_coroutine_threadsafe(
            mgr._mailbox.get(
                reply_up, _BLOB_DOWN, timeout_s=timeout_s,
                src_party=holder,
            ),
            mgr._loop,
        )
        req = objects.make_blob_request(fp, reply_up)
        metadata = {
            wire.BLOB_GET_KEY: json.dumps(
                req, separators=(",", ":"), sort_keys=True
            )
        }
        try:
            client = mgr._get_client(holder)
            send_cf = asyncio.run_coroutine_threadsafe(
                client.send_data(
                    [], f"{BLOB_REQ_PREFIX}{self.party}.{nonce}",
                    _BLOB_DOWN, metadata=metadata,
                ),
                mgr._loop,
            )
            send_cf.result(timeout=timeout_s)
        except Exception as exc:
            recv_cf.cancel()
            mgr.discard_empty_park(reply_up, _BLOB_DOWN)
            raise _HolderFailure(
                "send", f"BLOB_GET request could not be delivered: {exc!r}"
            ) from exc
        from rayfed_tpu.exceptions import PartyWaitTimeout

        try:
            msg = recv_cf.result(timeout=timeout_s + 5)
        except PartyWaitTimeout as exc:
            raise _HolderFailure(
                "timeout", f"no reply within {timeout_s}s"
            ) from exc
        except Exception as exc:
            raise _HolderFailure("timeout", repr(exc)) from exc
        if msg.error is not None:
            # Dead-holder fast-fail (Mailbox.get's src_party poison) or
            # a mid-pull death delivered by the health monitor.
            raise _HolderFailure(
                "dead", msg.error.get("msg", str(msg.error))
            )
        raw_rep = (msg.metadata or {}).get(wire.BLOB_PUT_KEY)
        rep: Dict[str, Any] = {}
        if raw_rep is not None:
            try:
                rep = objects.check_blob_reply_meta(json.loads(raw_rep))
            except Exception as exc:
                raise _HolderFailure(
                    "corrupt", f"malformed BLOB_PUT metadata: {exc!r}"
                ) from exc
        if rep.get("miss"):
            raise _HolderFailure(
                "miss", "holder does not hold these bytes"
            )
        data = bytes(msg.payload)
        got = wire.blob_fingerprint(data)
        if got != fp:
            raise _HolderFailure(
                "corrupt",
                f"arrived bytes fingerprint {got} != requested {fp}",
            )
        return data

    # -- serve (holder side) ----------------------------------------------

    def _observe_request(self, message) -> bool:
        """Server observer (transport loop thread): BLOB_GET request
        frames — identified by their ``wire.BLOB_GET_KEY`` metadata —
        are consumed here (ACKed, never enter the mailbox) and served
        off-loop from the store."""
        raw = (message.metadata or {}).get(wire.BLOB_GET_KEY)
        if raw is None:
            return False
        if message.error is not None:
            return True  # a poisoned request carries nothing to serve
        try:
            req = objects.check_blob_request(json.loads(raw))
        except Exception:
            logger.warning(
                "[%s] malformed BLOB_GET request from %s: %r",
                self.party, message.src_party, raw,
            )
            return True
        self._manager._codec_pool.submit(
            self._serve, message.src_party, req
        )
        return True

    def _serve(self, requester: str, req: Dict[str, Any]) -> None:
        """Codec-pool thread: push the stored bytes (or a miss notice)
        to the requester's reply key.  Ordinary DATA framing — striping
        / per-chunk CRC / reassembly apply to large blobs unchanged."""
        mgr = self._manager
        fp = req["fp"]
        data = self.store.get(fp)
        crc = None
        if data is None:
            self.stats["blob_serve_misses"] += 1
            bufs: list = []
            rep = objects.make_blob_reply_meta(fp, miss=True)
        else:
            self.stats["blob_serves"] += 1
            self.stats["blob_serve_bytes"] += len(data)
            bufs = [data]
            rep = objects.make_blob_reply_meta(fp, len(data))
        metadata = {
            wire.BLOB_PUT_KEY: json.dumps(
                rep, separators=(",", ":"), sort_keys=True
            )
        }
        try:
            client = mgr._get_client(requester)
            if (
                data is not None
                and client.checksum_enabled
                and len(data) < wire.SHARD_STREAM_THRESHOLD
            ):
                # Small replies: checksum here (off-loop); streamed /
                # striped replies chain their CRC per chunk as usual.
                from rayfed_tpu import native

                crc = native.crc32c_multi(bufs)
            cf = asyncio.run_coroutine_threadsafe(
                client.send_data(
                    bufs, req["rk"], _BLOB_DOWN, metadata=metadata,
                    crc=crc,
                ),
                mgr._loop,
            )
        except Exception:
            logger.exception(
                "[%s] blob serve of %s to %s could not be dispatched",
                self.party, fp, requester,
            )
            return

        def _done(f) -> None:
            exc = (
                f.exception() if not f.cancelled()
                else asyncio.CancelledError("transport stopped")
            )
            if exc is not None:
                # Best-effort: the requester's per-holder timeout (or
                # its own death) governs; it retries another holder.
                logger.warning(
                    "[%s] blob serve of %s to %s failed: %r",
                    self.party, fp, requester, exc,
                )

        cf.add_done_callback(_done)

    def close(self) -> None:
        """Shut the fetch pool down (manager.stop)."""
        with self._lock:
            pool, self._fetch_pool = self._fetch_pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    # -- introspection -----------------------------------------------------

    def stats_snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = dict(self.stats)
        out.update(self.store.stats)
        out["blob_cache_bytes"] = self.store.total_bytes()
        out["blob_pinned_bytes"] = self.store.pinned_bytes()
        out["blob_cache_entries"] = len(self.store.fingerprints())
        return out
