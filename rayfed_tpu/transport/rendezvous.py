"""Either-side-first rendezvous mailbox.

Reproduces the reference's event-dict race discipline
(``barriers.py:61-90`` sender side vs ``:324-345`` receiver side): data may
arrive before anyone asked for it, or a receiver may park before the data
exists — whichever side arrives first creates the entry.  The reference
mixes ``threading.Lock`` with asyncio inside a Ray actor (flagged as a
wart at ``barriers.py:303``); here everything runs on a single asyncio
loop, so no locks are needed at all.

Hardening beyond the reference:

- **Duplicate-delivery dedup**: a retry after a lost ACK re-delivers the
  same (up, down) key; consumed keys are remembered (bounded LRU) and
  re-deliveries are dropped instead of leaking a never-consumed entry.
- **TTL garbage collection**: undelivered payloads nobody ever recvs are
  expired after ``ttl_s`` (default: off until the manager wires it to the
  job's timeout), bounding mailbox memory.
- **Recv deadline**: ``get(..., timeout_s=...)`` raises ``TimeoutError``
  instead of parking forever, so a dead peer surfaces as an error on
  ``fed.get`` rather than a hang.
- **Peer-death fail-fast**: :meth:`Mailbox.fail_party` poisons every
  parked waiter expecting a party (and, until
  :meth:`Mailbox.clear_party_failure`, any new waiter on it) with an
  error message, so the transport's health monitor can turn "connection
  lost / peer unreachable" into a prompt ``RemoteError`` on ``fed.get``
  instead of a park until the recv backstop.  The reference is blind
  here (``barriers.py:244-248`` swallows send failures into False and
  its consumer never learns).
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import time
from typing import Any, Dict, Optional, Tuple

Key = Tuple[str, str]  # (upstream_seq_id, downstream_seq_id)

# How many consumed keys to remember for duplicate-delivery detection.
_CONSUMED_CACHE = 8192


@dataclasses.dataclass
class Message:
    src_party: str
    upstream_seq_id: str
    downstream_seq_id: str
    payload: bytes
    metadata: Dict[str, str]
    # Wall time the receiver spent reading the payload off the socket —
    # the honest denominator for receiver-side GB/s.
    read_seconds: float = 0.0
    # Poison marker: the producer's task/encode failed; dict with
    # party/type/msg (see exceptions.RemoteError.to_wire).  The recv path
    # raises instead of decoding.
    error: Optional[Dict[str, str]] = None


class _Entry:
    __slots__ = ("event", "message", "created_at", "expected_src")

    def __init__(self) -> None:
        self.event = asyncio.Event()
        self.message: Optional[Message] = None
        self.created_at = time.monotonic()
        # The party a parked waiter expects data from (None until a recv
        # declares it) — lets fail_party target exactly the waiters a
        # dead peer owes.
        self.expected_src: Optional[str] = None


class Mailbox:
    """Keyed (upstream_seq_id, downstream_seq_id) → one message slot.

    All methods must be called from the owning asyncio loop.
    """

    def __init__(self, ttl_s: Optional[float] = None) -> None:
        self._entries: Dict[Key, _Entry] = {}
        self._consumed: "collections.OrderedDict[Key, None]" = (
            collections.OrderedDict()
        )
        self._ttl_s = ttl_s
        # party -> wire-form error dict; recvs expecting these parties
        # fail immediately until clear_party_failure.
        self._dead_parties: Dict[str, Dict[str, str]] = {}
        # Every party that ever delivered data here — evidence of
        # reachability for the health monitor's loss-not-absence gate —
        # and the monotonic time of each party's latest delivery (a
        # fresh delivery IS liveness; the monitor must not count ping
        # failures against a party whose data is actively arriving).
        self._seen_parties: set = set()
        self._last_put: Dict[str, float] = {}
        # Immutable snapshot of the dead set for CROSS-THREAD readers
        # (get_stats polls from user threads; every other Mailbox method
        # is loop-thread-only).  Replaced wholesale on each mutation, so
        # a reader never iterates a dict the loop is resizing.
        self._dead_snapshot: frozenset = frozenset()
        self.stats: Dict[str, int] = {
            "dropped_duplicates": 0,
            "expired": 0,
            "peer_failed_recvs": 0,
        }

    def put(self, message: Message) -> None:
        if message.error is None:
            self._seen_parties.add(message.src_party)
            self._last_put[message.src_party] = time.monotonic()
        key = (message.upstream_seq_id, message.downstream_seq_id)
        if key in self._consumed:
            # Re-delivery of an already-consumed rendezvous (sender retry
            # after a lost ACK) — dropping it prevents an entry that no
            # recv will ever pop.
            self.stats["dropped_duplicates"] += 1
            return
        entry = self._entries.get(key)
        if entry is None:
            entry = _Entry()
            self._entries[key] = entry
        entry.message = message
        entry.event.set()

    def _mark_consumed(self, key: Key) -> None:
        self._consumed[key] = None
        self._consumed.move_to_end(key)
        while len(self._consumed) > _CONSUMED_CACHE:
            self._consumed.popitem(last=False)

    def try_take(self, key: Key) -> Optional[Message]:
        """Pop the message for ``key`` if it already arrived, else None.

        Non-blocking twin of :meth:`get` for the streaming-receive path:
        a push that landed before the sink was registered is taken from
        the mailbox instead (and the key marked consumed as usual)."""
        entry = self._entries.get(key)
        if entry is None or entry.message is None:
            return None
        self._entries.pop(key, None)
        self._mark_consumed(key)
        return entry.message

    def mark_delivered(self, src_party: str, key: Key) -> None:
        """Record an out-of-band (sink-consumed) delivery of ``key``.

        The payload never entered the mailbox, but the rendezvous must
        still be remembered as consumed (sender retries after a lost ACK
        are dups) and the delivery still counts as the party's liveness
        for the health monitor."""
        if src_party:
            self._seen_parties.add(src_party)
            self._last_put[src_party] = time.monotonic()
        self._mark_consumed(key)
        # A parked waiter entry for the same key (conflicting consumers)
        # is left untouched: recv and recv_stream on one key is a caller
        # bug, and failing the waiter here would mask it.

    async def get(
        self,
        upstream_seq_id: str,
        downstream_seq_id: str,
        timeout_s: Optional[float] = None,
        src_party: Optional[str] = None,
    ) -> Message:
        key = (str(upstream_seq_id), str(downstream_seq_id))
        entry = self._entries.get(key)
        if entry is None:
            entry = _Entry()
            self._entries[key] = entry
        if src_party is not None:
            entry.expected_src = src_party
        # A party already declared dead fails this recv immediately —
        # unless its data actually raced in first (prefer real data).
        if (
            entry.message is None
            and src_party is not None
            and src_party in self._dead_parties
        ):
            self.stats["peer_failed_recvs"] += 1
            self._entries.pop(key, None)
            self._mark_consumed(key)
            return Message(
                src_party, key[0], key[1], b"", {},
                error=dict(self._dead_parties[src_party]),
            )
        try:
            if timeout_s is None:
                await entry.event.wait()
            else:
                await asyncio.wait_for(entry.event.wait(), timeout=timeout_s)
        except asyncio.TimeoutError:
            # Only the parked-waiter entry is discarded; a message that
            # raced in concurrently has set the event and is returned.
            if entry.message is None:
                self._entries.pop(key, None)
                from rayfed_tpu.exceptions import PartyWaitTimeout

                raise PartyWaitTimeout(
                    f"recv of ({key[0]}, {key[1]}) timed out after "
                    f"{timeout_s}s",
                    missing_parties=(
                        [entry.expected_src] if entry.expected_src else []
                    ),
                ) from None
        # Pop: a rendezvous key is consumed exactly once (ref barriers.py:338-340).
        self._entries.pop(key, None)
        self._mark_consumed(key)
        assert entry.message is not None
        return entry.message

    def fail_party(
        self, party: str, error: Dict[str, str], poison_new: bool = True
    ) -> int:
        """Fail every parked waiter expecting ``party`` with ``error``
        (wire-form dict, see ``RemoteError.to_wire``); with
        ``poison_new`` (default), new recvs on it fail immediately until
        :meth:`clear_party_failure`.  Returns the number of waiters
        failed.  Loop-thread only, like every Mailbox method."""
        failed = 0
        for key, entry in list(self._entries.items()):
            if entry.message is None and entry.expected_src == party:
                entry.message = Message(
                    party, key[0], key[1], b"", {}, error=dict(error)
                )
                entry.event.set()
                failed += 1
        self.stats["peer_failed_recvs"] += failed
        if poison_new:
            self._dead_parties[party] = dict(error)
            self._dead_snapshot = frozenset(self._dead_parties)
        return failed

    def clear_party_failure(self, party: str) -> None:
        """The party is reachable again: stop failing new recvs on it."""
        self._dead_parties.pop(party, None)
        self._dead_snapshot = frozenset(self._dead_parties)

    def dead_parties(self):
        return set(self._dead_parties)

    def party_failure(self, party: str) -> Optional[Dict[str, str]]:
        """The stored wire-form error of a declared-dead ``party``
        (``None`` while it is considered alive).  Loop-thread only."""
        err = self._dead_parties.get(party)
        return dict(err) if err is not None else None

    def dead_parties_snapshot(self) -> frozenset:
        """Cross-thread-safe view of the dead set (see _dead_snapshot)."""
        return self._dead_snapshot

    def seen_parties(self):
        """Parties that have delivered data to this mailbox."""
        return set(self._seen_parties)

    def seconds_since_delivery(self, party: str) -> float:
        """Monotonic seconds since ``party`` last delivered data
        (``inf`` if never)."""
        t = self._last_put.get(party)
        return float("inf") if t is None else time.monotonic() - t

    def parties_with_waiters(self):
        """Parties that parked waiters currently expect data from."""
        return {
            e.expected_src
            for e in self._entries.values()
            if e.message is None and e.expected_src is not None
        }

    def gc(self, now: Optional[float] = None) -> int:
        """Expire undelivered messages older than the TTL; returns count."""
        if self._ttl_s is None:
            return 0
        now = time.monotonic() if now is None else now
        # An entry is GC-eligible only when data arrived but nobody
        # consumed it: a parked waiter's entry has message None (its own
        # timeout governs), and data+waiter resolves immediately anyway.
        expired = [
            key
            for key, entry in self._entries.items()
            if entry.message is not None and now - entry.created_at > self._ttl_s
        ]
        for key in expired:
            self._entries.pop(key, None)
        self.stats["expired"] += len(expired)
        return len(expired)

    def pending_count(self) -> int:
        return len(self._entries)

    def pending_bytes(self) -> int:
        return sum(
            len(e.message.payload)
            for e in self._entries.values()
            if e.message is not None
        )
