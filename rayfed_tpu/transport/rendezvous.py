"""Either-side-first rendezvous mailbox.

Reproduces the reference's event-dict race discipline
(``barriers.py:61-90`` sender side vs ``:324-345`` receiver side): data may
arrive before anyone asked for it, or a receiver may park before the data
exists — whichever side arrives first creates the entry.  The reference
mixes ``threading.Lock`` with asyncio inside a Ray actor (flagged as a
wart at ``barriers.py:303``); here everything runs on a single asyncio
loop, so no locks are needed at all.
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Any, Dict, Optional, Tuple

Key = Tuple[str, str]  # (upstream_seq_id, downstream_seq_id)


@dataclasses.dataclass
class Message:
    src_party: str
    upstream_seq_id: str
    downstream_seq_id: str
    payload: bytes
    metadata: Dict[str, str]
    # Wall time the receiver spent reading the payload off the socket —
    # the honest denominator for receiver-side GB/s.
    read_seconds: float = 0.0


class _Entry:
    __slots__ = ("event", "message")

    def __init__(self) -> None:
        self.event = asyncio.Event()
        self.message: Optional[Message] = None


class Mailbox:
    """Keyed (upstream_seq_id, downstream_seq_id) → one message slot.

    All methods must be called from the owning asyncio loop.
    """

    def __init__(self) -> None:
        self._entries: Dict[Key, _Entry] = {}

    def put(self, message: Message) -> None:
        key = (message.upstream_seq_id, message.downstream_seq_id)
        entry = self._entries.get(key)
        if entry is None:
            entry = _Entry()
            self._entries[key] = entry
        entry.message = message
        entry.event.set()

    async def get(self, upstream_seq_id: str, downstream_seq_id: str) -> Message:
        key = (str(upstream_seq_id), str(downstream_seq_id))
        entry = self._entries.get(key)
        if entry is None:
            entry = _Entry()
            self._entries[key] = entry
        await entry.event.wait()
        # Pop: a rendezvous key is consumed exactly once (ref barriers.py:338-340).
        self._entries.pop(key, None)
        assert entry.message is not None
        return entry.message

    def pending_count(self) -> int:
        return len(self._entries)
