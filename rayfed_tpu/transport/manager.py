"""TransportManager — send/recv proxies on one asyncio loop thread.

The reference hosts its transport in two named Ray actors
(``SendProxyActor`` / ``RecverProxyActor-{party}``, ``barriers.py:184-351``)
with ``max_concurrency=1000`` so many ``get_data`` calls can park.  Our
party controller is a single process, so both proxies live on one asyncio
event loop running in a dedicated thread: thousands of pending recvs are
just parked coroutines, and sends are pipelined frames — no actor
round-trips, no object-store copies.

Payload encode/decode runs on a small codec thread pool so the loop never
blocks on serialization, and received device-array leaves are put back on
local devices off-loop as well.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from rayfed_tpu import telemetry
from rayfed_tpu.config import ClusterConfig, JobConfig, RetryPolicy
from rayfed_tpu.executor import LocalRef
from rayfed_tpu.transport import local
from rayfed_tpu.transport import secagg as secagg_keys
from rayfed_tpu.transport import tls as tls_utils
from rayfed_tpu.transport import wire
from rayfed_tpu.transport.client import SendError, TransportClient
from rayfed_tpu.transport.rendezvous import Mailbox, Message
from rayfed_tpu.transport.server import TransportServer

logger = logging.getLogger(__name__)


# Transport options the client actually consumes; everything else in a
# party's transport_options/grpc_options is loudly reported as ignored
# (the reference silently dropped unknown gRPC channel args — an
# operator typo like "tiemout_s" then just... did nothing).
_KNOWN_TRANSPORT_OPTIONS = frozenset(
    {"timeout_s", "max_message_size", "checksum", "connections_per_peer",
     "stripe_rails", "heartbeat_interval_s", "death_deadline_s",
     "local_link"}
)
# Reference-style gRPC channel-arg keys accepted for drop-in compat.
_COMPAT_TRANSPORT_OPTIONS = {
    "grpc.max_send_message_length": "max_message_size",
}
# Recognized-but-inapplicable: there is no gRPC authority to override
# on a raw socket transport.  Reported with the ignored keys.
_INAPPLICABLE_TRANSPORT_OPTIONS = frozenset({"grpc.default_authority"})


def _validate_health_knobs(heartbeat_s: float, deadline_s: float) -> None:
    """Shared validation of the per-party health-monitor options
    (``heartbeat_interval_s`` / ``death_deadline_s``) — surfaced through
    ``effective_transport_options`` instead of living as module
    constants, and validated wherever they enter."""
    if not (heartbeat_s > 0):
        raise ValueError(
            f"heartbeat_interval_s must be > 0, got {heartbeat_s}"
        )
    if deadline_s < heartbeat_s:
        raise ValueError(
            f"death_deadline_s ({deadline_s}) must be >= "
            f"heartbeat_interval_s ({heartbeat_s}) — a deadline shorter "
            f"than one heartbeat would declare every party dead on its "
            f"first missed ping"
        )


class RosterState:
    """Epoch-numbered live-membership view (elastic party membership).

    The cluster config stays the static universe of parties that COULD
    participate; the roster is the subset that currently DOES, stamped
    with a monotonically increasing **epoch**.  Epochs advance only at
    round boundaries, announced by the quorum round's coordinator in its
    result broadcast (``fl.quorum``) — every controller applies the same
    announcement, so the roster is identical everywhere without a
    consensus protocol.  ``fed.join()`` / ``fed.leave()`` / monitor-
    declared death all funnel through those announcements; no fed
    runtime restarts on churn.

    Frames of quorum rounds are stamped with the sender's epoch
    (``wire.EPOCH_TAG_KEY``) and the receiving server rejects
    cross-epoch frames loudly — see ``TransportServer.epoch_provider``.

    Thread-safe: read from the transport loop (epoch checks), driver
    threads, and the health monitor.
    """

    def __init__(self, members: Sequence[str]) -> None:
        self._lock = threading.Lock()
        self._epoch = 0
        self._members = tuple(sorted(members))
        self._leave_requested = False

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def members(self) -> tuple:
        with self._lock:
            return self._members

    def snapshot(self) -> tuple:
        """``(epoch, members)`` read atomically."""
        with self._lock:
            return self._epoch, self._members

    def is_member(self, party: str) -> bool:
        with self._lock:
            return party in self._members

    def apply(self, epoch: int, members: Sequence[str]) -> bool:
        """Apply an announced roster; returns True if it advanced.

        Stale announcements (epoch older than current) are ignored with
        a warning — a late broadcast replay must not roll membership
        back.  An equal-epoch announcement with DIFFERENT members is a
        protocol bug and raises.
        """
        epoch = int(epoch)
        members = tuple(sorted(members))
        with self._lock:
            if epoch < self._epoch:
                logger.warning(
                    "ignoring stale roster announcement (epoch %d < "
                    "current %d)", epoch, self._epoch,
                )
                return False
            if epoch == self._epoch:
                if members != self._members:
                    raise ValueError(
                        f"conflicting rosters for epoch {epoch}: "
                        f"{members} vs {self._members}"
                    )
                return False
            logger.info(
                "roster epoch %d -> %d: members %s -> %s",
                self._epoch, epoch, self._members, members,
            )
            self._epoch = epoch
            self._members = members
            return True

    def advance(self, members: Sequence[str]) -> int:
        """Coordinator-side: bump the epoch with a new member set and
        return the new epoch (the announcement payload)."""
        with self._lock:
            self._epoch += 1
            self._members = tuple(sorted(members))
            logger.info(
                "roster advanced to epoch %d: %s",
                self._epoch, self._members,
            )
            return self._epoch

    # -- graceful departure (fed.leave) -----------------------------------

    def request_leave(self) -> None:
        """Mark this party as wanting out; the quorum round driver picks
        the flag up at the next round boundary (``fed.leave``)."""
        with self._lock:
            self._leave_requested = True

    def consume_leave_request(self) -> bool:
        with self._lock:
            requested, self._leave_requested = self._leave_requested, False
            return requested


# Rendezvous-key prefix of roster membership REQUESTS (join / leave):
# routed around the mailbox via a server observer into the manager's
# membership inbox, which the quorum coordinator drains at round
# boundaries.  Join WELCOMES ride ordinary rendezvous keys (the joiner
# parks a recv on them).
ROSTER_REQ_PREFIX = "roster.req."


def roster_successor(
    members: Sequence[str], coordinator: str, dead: Sequence[str] = (),
) -> Optional[str]:
    """Deterministic coordinator succession: the next alive party after
    ``coordinator`` on the sorted roster ring.

    Every controller derives the successor LOCALLY from the same inputs
    — the epoch-numbered roster members and the (departing or declared-
    dead) coordinator — so a coordinator crash or graceful ``fed.leave``
    needs no election protocol: walk the sorted ring starting just past
    the coordinator's position (wrapping), return the first candidate
    that is a member and not in ``dead``.  ``None`` when nobody else is
    alive.  The walk starts from the coordinator's canonical position
    whether or not it is still a member, so iterated successions (A
    dies, then B dies) land on the same party as a one-shot derivation
    from the pinned coordinator over the surviving roster.
    """
    ring = sorted(set(members) | {coordinator})
    i = ring.index(coordinator)
    skip = set(dead) | {coordinator}
    candidates = set(members)
    for p in ring[i + 1:] + ring[:i]:
        if p in candidates and p not in skip:
            return p
    return None


def partition_regions(
    members: Sequence[str], region_size: int
) -> List[List[str]]:
    """Deterministic two-level partition of the roster into regions.

    Contiguous slices of the **sorted** member list, ``region_size``
    parties each (last region short) — the same canonical order every
    other cross-controller decision uses (sampling, stripe ownership,
    ring neighbors), so every controller derives the identical
    partition from the identical roster epoch with zero negotiation.
    The hierarchy topology (:mod:`rayfed_tpu.fl.hierarchy`) builds on
    this: region ``g`` runs its own chunk-striped ring, region
    coordinators carry integer partial sums up to the root.
    """
    if int(region_size) < 1:
        raise ValueError(
            f"region_size must be >= 1, got {region_size}"
        )
    ps = sorted(members)
    if not ps:
        raise ValueError("cannot partition an empty roster")
    s = int(region_size)
    return [ps[i : i + s] for i in range(0, len(ps), s)]


def branch_groups(
    node_ids: Sequence[int], branch: int
) -> List[Tuple[int, List[int]]]:
    """Deterministic constant-degree grouping of one tree level.

    Groups node ids by ``id // branch`` over the FULL id range of the
    level — NOT by packing the surviving ids densely — so a node's
    parent is a pure function of its own id and never moves when a
    sibling's subtree dies.  Every controller derives the identical
    grouping from the identical roster epoch, the same zero-negotiation
    contract as :func:`partition_regions`; the multi-level hierarchy
    (:mod:`rayfed_tpu.fl.hierarchy`) applies this rule recursively
    until a single top node remains.  Returns ``(parent_id, children)``
    pairs sorted by parent id, children in ascending id order.
    """
    if int(branch) < 2:
        raise ValueError(f"branch must be >= 2, got {branch}")
    b = int(branch)
    grouped: Dict[int, List[int]] = {}
    for cid in sorted(node_ids):
        grouped.setdefault(cid // b, []).append(cid)
    return sorted(grouped.items())


def ring_neighbors(parties: Sequence[str], party: str) -> tuple:
    """``(predecessor, successor)`` of ``party`` on the sorted ring.

    The ring order is the SORTED party list — the same canonical order
    every other cross-controller decision uses (sampling, stripe
    ownership), so all parties derive identical neighbors without
    coordination.  At N=2 the single peer is both neighbors; at N=1 the
    party is its own.
    """
    ring = sorted(parties)
    try:
        i = ring.index(party)
    except ValueError:
        raise ValueError(f"{party!r} is not in the ring {ring}") from None
    return ring[i - 1], ring[(i + 1) % len(ring)]


class TransportManager:
    def __init__(
        self,
        cluster_config: ClusterConfig,
        job_config: JobConfig,
    ) -> None:
        self._cluster = cluster_config
        self._job = job_config
        self._party = cluster_config.current_party

        self._loop = asyncio.new_event_loop()
        self._loop_thread: Optional[threading.Thread] = None
        self._started = threading.Event()

        self._mailbox = Mailbox(ttl_s=job_config.mailbox_ttl_s)
        self._gc_task: Optional[asyncio.TimerHandle] = None
        self._health_task: Optional[asyncio.Task] = None
        # Parties whose server acked one of our sends — reachability
        # evidence for the health monitor (set.add is atomic; read on
        # the loop thread, written from send callbacks).
        self._peers_acked: set = set()
        my_cfg = cluster_config.party_config(self._party)
        listen_addr = my_cfg.listen_addr or my_cfg.address
        self._server = TransportServer(
            party=self._party,
            listen_addr=listen_addr,
            mailbox=self._mailbox,
            max_message_size=job_config.cross_silo_messages_max_size,
            ssl_context=tls_utils.server_ssl_context(cluster_config.tls_config),
        )
        self._clients: Dict[str, TransportClient] = {}
        self._clients_lock = threading.Lock()
        self._codec_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix=f"rayfed-codec-{self._party}"
        )
        self.stats: Dict[str, Any] = {
            "send_op_count": 0,
            "send_bytes": 0,
            "send_seconds": 0.0,
            # Payload→wire-buffers encode time on the codec pool (the
            # "encode" stage of the send-path breakdown; the arena copy
            # is billed client-side as send_copy_s).
            "send_encode_s": 0.0,
        }
        # Per-destination send wall time (encode handoff → ACK), summed
        # over sends: surfaces which peer a fan-out actually waits on.
        # Read-modify-write from codec AND loop threads — guarded by a
        # lock so overlapping completions to one destination can't lose
        # an increment.
        self._dest_lock = threading.Lock()
        self._dest_seconds: Dict[str, float] = {}
        self._dest_ops: Dict[str, int] = {}
        # Per-destination transport-option keys that were ignored (S3:
        # never silently dropped) + one-time warning bookkeeping.
        self._ignored_options: Dict[str, list] = {}
        self._warned_ignored: set = set()
        # recv_stream bookkeeping: rendezvous key -> src party, so the
        # health monitor can fail chunk-sink waits (which never park in
        # the mailbox) when their source party dies.  Loop thread only.
        self._stream_srcs: Dict[tuple, str] = {}
        # Elastic membership: the live roster (epoch + members) plus the
        # membership-request inbox (join/leave control messages from
        # peers, consumed by a server observer; the quorum coordinator
        # drains it at round boundaries).  deque append/popleft are
        # atomic, so the loop thread appends and driver threads drain
        # without a lock.
        import collections as _collections

        self.roster = RosterState(cluster_config.parties)
        self._membership_inbox: "_collections.deque" = _collections.deque()
        self._server.epoch_provider = lambda: self.roster.epoch
        self._server._observers.append(self._observe_membership)
        # Secure-aggregation key agreement (transport/secagg.py): one
        # ephemeral keypair per manager (per fed.init session), NOT
        # module-global — several in-process parties each hold their
        # own.  Published in every HELLO this party sends or answers;
        # fl/secagg.py derives pairwise mask seeds from it.
        self.secagg_keys = secagg_keys.KeyAgreement(self._party)
        self._server.secagg = self.secagg_keys
        # Content-addressed pull-on-demand object plane (transport/
        # objectstore.py): fingerprint handles for large immutable
        # objects, BLOB_GET/BLOB_PUT pulls on the existing frame
        # machinery, bounded content cache.  The observer consumes
        # BLOB_GET request frames like the membership observer consumes
        # roster requests.
        from rayfed_tpu.transport.objectstore import ObjectPlane

        self.objects = ObjectPlane(
            self, budget_bytes=job_config.blob_cache_budget_bytes
        )
        self._server._observers.append(self.objects._observe_request)
        # Per-manager transfer log (rayfed_tpu/metrics.py): in-process
        # multi-party tests/benches used to conflate every party's
        # transfers into the module-global ring (the KeyAgreement
        # per-manager lesson from the secagg work) — each manager now
        # owns its ring; the module global remains a documented
        # runtime-less fallback.
        from rayfed_tpu import metrics as _metrics

        self.transfer_log = _metrics.TransferLog()
        # Flight-recorder trace collection (rayfed_tpu/telemetry.py):
        # peers pull this party's span-ring window via a TRACE_GET
        # request frame consumed by a server observer — the BLOB_GET
        # shape — answered with a JSON record window on the requester's
        # nonce reply key.  Serving works even with the recorder
        # disarmed (an empty window, marked armed=False), so a mixed
        # fleet degrades loudly rather than hanging the collector.
        self._server._observers.append(self._observe_trace_request)
        # Set by api.init: () -> Optional[jax.sharding.Mesh].  Received
        # shard-encoded leaves whose sender sharding fits this mesh are
        # device_put with the equivalent local NamedSharding.
        self.mesh_provider = None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        def _run_loop():
            asyncio.set_event_loop(self._loop)
            self._started.set()
            self._loop.run_forever()

        # Warm the native codec build up front so the first transfer never
        # pays (or serializes behind) a g++ compile inside _get_client.
        from rayfed_tpu import native

        native.is_available()

        self._loop_thread = threading.Thread(
            target=_run_loop, name=f"rayfed-transport-{self._party}", daemon=True
        )
        self._loop_thread.start()
        self._started.wait()
        # Synchronous barrier: listener must be up before init returns
        # (parity with ray.get(actor.is_ready.remote()), barriers.py:379).
        fut = asyncio.run_coroutine_threadsafe(self._server.start(), self._loop)
        fut.result(timeout=30)

        def _periodic_gc():
            self._mailbox.gc()
            self._gc_task = self._loop.call_later(30.0, _periodic_gc)

        self._gc_task = self._loop.call_soon_threadsafe(
            lambda: self._loop.call_later(30.0, _periodic_gc)
        )
        if self._job.peer_failfast:
            self._loop.call_soon_threadsafe(
                lambda: setattr(
                    self, "_health_task",
                    self._loop.create_task(self._health_monitor()),
                )
            )

    async def _health_monitor(self) -> None:
        """Peer-death fail-fast: ping parties that parked recvs are
        waiting on; after ``peer_death_pings`` consecutive failures, fail
        those recvs with a ``RemoteError`` naming the party instead of
        letting them park until the recv backstop (improves on reference
        ``barriers.py:244-248``, which leaves the consumer blind).  A
        declared-dead party keeps being pinged and is un-poisoned the
        moment it answers again.

        A ping only fails when the peer's transport cannot answer a
        1-RTT control frame within the interval — its event loop serves
        pings independently of task compute, so a slow-but-healthy party
        does not trip this (the generous recv backstop stays the only
        limit on compute time).
        """
        from rayfed_tpu.exceptions import RemoteError

        base_interval = self._job.peer_health_interval_s
        default_pings = max(1, int(self._job.peer_death_pings))
        tick = base_interval
        fails: Dict[str, int] = {}
        # Fail-fast covers connection LOSS, not never-connected: a party
        # only becomes eligible after evidence of reachability — a
        # successful health ping, a delivered message (mailbox), or an
        # acked send (self._peers_acked).  Cross-silo parties routinely
        # start minutes apart, and a not-up-yet peer must park recvs
        # (bounded by the backstop), not get declared dead.
        ever_reachable: set = set()
        # Previous cycle's per-party received-byte counters (including
        # bytes of payloads still mid-read): a counter that moved since
        # the last cycle is proof of life even when control pings queue
        # behind the bulk transfer and time out — a multi-GB push must
        # not get its sender declared dead mid-transfer (the parked
        # recvs would be failed AND their keys marked consumed, so the
        # transfer's eventual completion would be dropped as a dup).
        rx_prev: Dict[str, int] = {}

        async def probe(party: str, hb_s: float) -> bool:
            # The ping deadline follows the PARTY'S OWN heartbeat, not
            # the shared tick: one party configuring an aggressive
            # heartbeat shrinks the probe cadence for everyone, but it
            # must not shrink everyone's ping timeout — a healthy
            # slow-RTT peer would read as dead.
            try:
                return await asyncio.wait_for(
                    self._get_client(party).ping(
                        timeout_s=min(1.0, hb_s), ctl=True
                    ),
                    timeout=max(tick, min(1.0, hb_s)),
                )
            except Exception:
                return False

        while True:
            await asyncio.sleep(tick)
            parties = sorted(
                self._mailbox.parties_with_waiters()
                | self._mailbox.dead_parties()
                # Chunk-sink waits (streaming/ring aggregation) never
                # park in the mailbox — monitor their source parties
                # too, or a peer dying mid reduce-scatter would leave
                # the aggregator blind until the recv backstop.
                | self._stream_sink_parties()
            )
            # Per-party health knobs (heartbeat_interval_s /
            # death_deadline_s transport options): the loop ticks at the
            # FASTEST configured heartbeat among the monitored parties,
            # and each party's death threshold is its own deadline
            # expressed in ticks — defaults reproduce the job-level
            # peer_health_interval_s × peer_death_pings behavior bit for
            # bit.  The tick adapts one cycle late, which is fine: the
            # deadline is what operators reason about.
            knobs: Dict[str, tuple] = {}
            for p in parties:
                try:
                    knobs[p] = self._party_health_knobs(p)
                except Exception:
                    knobs[p] = (
                        base_interval, base_interval * default_pings
                    )
            tick = min(
                [base_interval] + [hb for hb, _ in knobs.values()]
            )
            # Consecutive means consecutive: a party that left the
            # monitored set (its recvs resolved) starts from zero next
            # time it parks — stale counts from old blips must not
            # combine with a fresh transient into a false death.
            fails = {p: c for p, c in fails.items() if p in parties}
            ever_reachable |= self._mailbox.seen_parties()
            ever_reachable |= self._peers_acked
            # Concurrent probes: one unreachable party must not delay
            # (and thereby slow detection for) the others.
            results = await asyncio.gather(
                *(probe(p, knobs[p][0]) for p in parties)
            )
            rx_now = self._server.receive_progress()
            for party, ok in zip(parties, results):
                # Fresh arriving bytes are liveness regardless of the
                # ping: a party mid-bulk-transfer can be slow to answer
                # control frames, but its data actively landing (even
                # partially, mid-payload) proves it isn't dead.
                if not ok and rx_now.get(party, 0) != rx_prev.get(party, 0):
                    ok = True
                if not ok and self._mailbox.seconds_since_delivery(
                    party
                ) <= tick:
                    ok = True
                if ok:
                    ever_reachable.add(party)
                    fails.pop(party, None)
                    if party in self._mailbox.dead_parties():
                        logger.info(
                            "[%s] party %s reachable again; clearing "
                            "fail-fast poison", self._party, party,
                        )
                        self._mailbox.clear_party_failure(party)
                elif (
                    party in ever_reachable
                    and party not in self._mailbox.dead_parties()
                ):
                    fails[party] = fails.get(party, 0) + 1
                    deadline_s = knobs[party][1]
                    threshold = max(1, int(round(deadline_s / tick)))
                    if fails[party] >= threshold:
                        logger.warning(
                            "[%s] party %s unreachable (%d consecutive "
                            "pings, death deadline %.1fs); failing its "
                            "pending recvs",
                            self._party, party, fails[party], deadline_s,
                        )
                        err = RemoteError(
                            party,
                            "ConnectionError",
                            f"party {party!r} is unreachable "
                            f"({fails[party]} consecutive health pings "
                            f"failed over ~{fails[party] * tick:.0f}s, "
                            f"death deadline {deadline_s:.1f}s); "
                            f"its pending sends will never arrive",
                        ).to_wire()
                        self._mailbox.fail_party(party, err)
                        self._fail_party_sinks(party, err)
            rx_prev = rx_now

    def _stream_sink_parties(self) -> set:
        """Source parties of still-registered chunk sinks (loop thread).

        Also purges bookkeeping for sinks that were consumed or
        cancelled since the last cycle, so the map cannot grow beyond
        the in-flight registrations.
        """
        live = {
            key: src
            for key, src in self._stream_srcs.items()
            if self._server.peek_chunk_sink(key) is not None
        }
        self._stream_srcs = live
        return set(live.values())

    def _fail_party_sinks(self, party: str, err: Dict[str, str]) -> None:
        """Deliver a dead party's failure to its pending chunk sinks —
        the stream analogue of ``Mailbox.fail_party`` (loop thread)."""
        for key, src in list(self._stream_srcs.items()):
            if src != party:
                continue
            self._stream_srcs.pop(key, None)
            sink = self._server.take_chunk_sink(key)
            if sink is None:
                continue
            try:
                sink.on_error(err)
            except Exception:  # pragma: no cover - sink bug
                logger.exception(
                    "[%s] chunk sink failure delivery raised", self._party
                )

    def stop(self) -> None:
        async def _shutdown():
            for client in self._clients.values():
                await client.close()
            await self._server.stop()
            # Cancel parked recvs so shutdown doesn't leak pending tasks.
            current = asyncio.current_task()
            for task in asyncio.all_tasks():
                if task is not current:
                    task.cancel()

        if self._loop_thread is None:
            return
        try:
            asyncio.run_coroutine_threadsafe(_shutdown(), self._loop).result(timeout=10)
        except Exception:  # pragma: no cover
            logger.exception("[%s] transport shutdown error", self._party)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._loop_thread.join(timeout=10)
        self._loop.close()
        self._loop_thread = None
        self._codec_pool.shutdown(wait=False)
        self.objects.close()

    # -- client construction --------------------------------------------------

    def _merged_options(self, dest_party: str) -> Dict[str, Any]:
        """Per-destination options, per-party overriding global (ref :250-268).

        Unknown keys are NOT silently dropped: they are recorded per
        destination (see :meth:`effective_transport_options`) and a
        loud one-time warning lists every ignored key — an operator
        typo must be diagnosable, not a silent no-op.
        """
        from rayfed_tpu import native

        opts: Dict[str, Any] = {
            "timeout_s": self._job.cross_silo_timeout_s,
            "max_message_size": self._job.cross_silo_messages_max_size,
            # Default on only when the fast C++ path built; the pure-
            # Python CRC is ~MB/s and would stall large pushes.  Explicit
            # per-party {"checksum": True} still forces it.
            "checksum": native.is_available(),
            # Connections per destination: concurrent pushes to one party
            # ride different sockets (no head-of-line blocking), and a
            # single striped payload fans its chunks across all of them.
            "connections_per_peer": 2,
            # Health-monitor knobs (peer-death fail-fast), surfaced as
            # validated per-party options instead of module constants:
            # probe cadence and how long a party may stay unreachable
            # before its pending recvs are failed.
            "heartbeat_interval_s": self._job.peer_health_interval_s,
            "death_deadline_s": (
                self._job.peer_health_interval_s
                * max(1, int(self._job.peer_death_pings))
            ),
            # Per-link transport backend (transport/local.py): "auto"
            # upgrades a link to the peer's AF_UNIX listener (same
            # host) or the in-process shared-memory handoff (same
            # process); "off" (the default) pins TCP — existing
            # topologies keep their exact wire behavior unless opted
            # in per-job or per-party.
            "local_link": getattr(self._job, "local_link", "off"),
        }
        party_opts = dict(self._cluster.party_config(dest_party).transport_options)
        # Accept reference-style gRPC channel-arg keys for drop-in compat.
        for compat_key, real_key in _COMPAT_TRANSPORT_OPTIONS.items():
            if compat_key in party_opts:
                opts[real_key] = party_opts.pop(compat_key)
        unknown = []
        inapplicable = []
        for key in list(party_opts):
            if key in _KNOWN_TRANSPORT_OPTIONS:
                opts[key] = party_opts.pop(key)
            else:
                party_opts.pop(key)
                if key in _INAPPLICABLE_TRANSPORT_OPTIONS:
                    inapplicable.append(key)
                else:
                    unknown.append(key)
        unknown.sort()
        inapplicable.sort()
        # Everything not applied is reported through the accessor;
        # recognized-but-inapplicable keys (a reference config's
        # grpc.default_authority) are named separately in the warning
        # so they don't read as operator typos.
        self._ignored_options[dest_party] = unknown + inapplicable
        if (unknown or inapplicable) and dest_party not in self._warned_ignored:
            self._warned_ignored.add(dest_party)
            logger.warning(
                "[%s] transport options for %s contain keys this "
                "transport does not use — IGNORED: %s%s (known keys: "
                "%s; compat aliases: %s)",
                self._party, dest_party, unknown or "[]",
                f"; recognized but inapplicable on a raw-socket "
                f"transport: {inapplicable}" if inapplicable else "",
                sorted(_KNOWN_TRANSPORT_OPTIONS),
                sorted(_COMPAT_TRANSPORT_OPTIONS),
            )
        opts["heartbeat_interval_s"] = float(opts["heartbeat_interval_s"])
        opts["death_deadline_s"] = float(opts["death_deadline_s"])
        _validate_health_knobs(
            opts["heartbeat_interval_s"], opts["death_deadline_s"]
        )
        return opts

    def _party_health_knobs(self, dest_party: str) -> tuple:
        """``(heartbeat_interval_s, death_deadline_s)`` for one party —
        the per-party transport options with job-config defaults,
        validated.  Light-weight twin of :meth:`_merged_options` for the
        health monitor's per-cycle reads (no ignored-key bookkeeping)."""
        opts = self._cluster.party_config(dest_party).transport_options
        hb = float(
            opts.get("heartbeat_interval_s",
                     self._job.peer_health_interval_s)
        )
        dd = float(
            opts.get(
                "death_deadline_s",
                hb * max(1, int(self._job.peer_death_pings)),
            )
        )
        _validate_health_knobs(hb, dd)
        return hb, dd

    def effective_transport_options(self, dest_party: str) -> Dict[str, Any]:
        """The merged options a client to ``dest_party`` actually runs
        with, plus every per-party key that was ignored — the operator
        debugging accessor for "which knob actually applied".

        Reflects a live client when one exists (post-init mutations
        like :meth:`set_max_message_size` show through); otherwise the
        merge that WOULD apply on first contact.
        """
        opts = self._merged_options(dest_party)
        with self._clients_lock:
            client = self._clients.get(dest_party)
        link_info = None
        if client is not None:
            opts["timeout_s"] = client._timeout_s
            opts["max_message_size"] = client._max_message_size
            opts["checksum"] = client.checksum_enabled
            opts["connections_per_peer"] = client._pool_size
            opts["stripe_rails"] = client._stripe_rails()
            opts["local_link"] = client._local_mode
            # The LIVE backend decision too (mode is the ask, backend
            # the outcome): {mode, backend, decided, fallback} — the
            # "did my link actually upgrade, and if not why" accessor.
            link_info = client.local_link_info()
        return {
            "party": dest_party,
            "options": opts,
            "ignored_keys": list(self._ignored_options.get(dest_party, [])),
            "metadata": self.merged_metadata(dest_party),
            "local_link": link_info,
        }

    def set_max_message_size(self, max_bytes: int) -> None:
        """Mutate the cross-silo message-size cap post-init.

        Applies atomically to the server and every live client on the
        transport loop; future clients inherit it through the job
        config.  Rejects with a clear error while any send is
        mid-flight — a torn apply (some frames under the old cap, the
        ACK under the new) is exactly the confusion this guards
        against.  Per-party explicit overrides are replaced too: an
        explicit runtime mutation wins over static config.
        """
        max_bytes = int(max_bytes)
        if max_bytes <= 0:
            raise ValueError(
                f"max message length must be positive, got {max_bytes}"
            )

        async def _apply():
            # fedlint: disable=FED001 — bounded hold: sync holders of _clients_lock only do dict ops / lazy client construction (no I/O, connections open on the loop), so this dict snapshot cannot park the loop meaningfully
            with self._clients_lock:
                clients = dict(self._clients)
            busy = sorted(
                p for p, c in clients.items() if c.has_inflight_sends()
            )
            if busy:
                raise RuntimeError(
                    f"cannot change max message length while sends are "
                    f"in flight to {busy}; wait for them to drain "
                    f"(e.g. fed.shutdown's wait_sending, or resolve "
                    f"the pending send refs) and retry"
                )
            for c in clients.values():
                c._max_message_size = max_bytes
            self._server._max_message_size = max_bytes

        asyncio.run_coroutine_threadsafe(_apply(), self._loop).result(
            timeout=30
        )
        # Future clients (and _merged_options defaults) follow the job
        # config — runtime.job_config is this same object.
        self._job.cross_silo_messages_max_size = max_bytes

    def merged_metadata(self, dest_party: str) -> Dict[str, str]:
        meta = dict(self._job.metadata)
        meta.update(self._cluster.party_config(dest_party).metadata)
        return meta

    def _get_client(self, dest_party: str) -> TransportClient:
        # Called from codec-pool threads and ping callers concurrently.
        with self._clients_lock:
            client = self._clients.get(dest_party)
            if client is None:
                opts = self._merged_options(dest_party)
                client = TransportClient(
                    src_party=self._party,
                    dest_party=dest_party,
                    address=self._cluster.party_config(dest_party).address,
                    retry_policy=self._job.retry_policy,
                    timeout_s=float(opts["timeout_s"]),
                    max_message_size=int(opts["max_message_size"]),
                    metadata=self.merged_metadata(dest_party),
                    ssl_context=tls_utils.client_ssl_context(self._cluster.tls_config),
                    checksum=bool(opts.get("checksum", True)),
                    pool_size=int(opts.get("connections_per_peer", 2)),
                    loop=self._loop,
                    # Rails a striped payload fans over; None = host-
                    # adaptive (striping off on few-core hosts).
                    stripe_rails=opts.get("stripe_rails"),
                    # Known-dead fast-fail: the retry ladder consults
                    # the health monitor's dead set (thread-safe
                    # snapshot) and skips the backoff ladder against a
                    # destination already declared dead — one attempt,
                    # no 65s of retries against a corpse.
                    dead_check=(
                        lambda p=dest_party:
                        p in self._mailbox.dead_parties_snapshot()
                    ),
                    secagg=self.secagg_keys,
                    local_link=str(opts.get("local_link", "off")),
                    # An explicit per-party/job checksum survives local-
                    # link CRC elision: the operator pinned it.
                    checksum_pinned=(
                        "checksum"
                        in self._cluster.party_config(
                            dest_party
                        ).transport_options
                    ),
                )
                self._clients[dest_party] = client
            return client

    # -- send path (SendProxy role) ------------------------------------------

    def _send_poison(
        self, dest_party: str, upstream_seq_id: Any, downstream_seq_id: Any,
        exc: BaseException,
    ) -> LocalRef:
        """Poison the promised rendezvous key on the consumer side.

        Improves on reference ``barriers.py:244-248`` (send failure →
        ``False`` + log; the peer's recv parks until its backstop): the
        consumer's ``fed.get`` raises :class:`RemoteError` within the
        round-trip time, carrying the producer's exception.

        Returns a LocalRef resolving when the poison delivery finished
        (True/False) — callers chain the user-visible send result on it so
        ``wait_sending``/``shutdown`` can't cancel an in-flight poison.
        """
        from rayfed_tpu.exceptions import RemoteError

        done = LocalRef()
        err = RemoteError.from_exception(self._party, exc).to_wire()
        try:
            client = self._get_client(dest_party)
            cf = asyncio.run_coroutine_threadsafe(
                client.send_data(
                    [], str(upstream_seq_id), str(downstream_seq_id), error=err
                ),
                self._loop,
            )

            def _poison_done(f):
                # exception() on a cancelled future (shutdown cancelling
                # loop tasks) RAISES instead of returning — guard it, or
                # `done` never resolves and wait_sending hangs forever.
                e = (
                    f.exception()
                    if not f.cancelled()
                    else asyncio.CancelledError("transport stopped")
                )
                if e is not None:
                    logger.warning(
                        "[%s] failed to poison (%s, %s) at %s: %r",
                        self._party, upstream_seq_id, downstream_seq_id,
                        dest_party, e,
                    )
                done.set_result(e is None)

            cf.add_done_callback(_poison_done)
        except Exception as e:  # pragma: no cover - client construction
            logger.warning(
                "[%s] cannot poison (%s, %s) at %s: %r",
                self._party, upstream_seq_id, downstream_seq_id, dest_party, e,
            )
            done.set_result(False)
        return done

    def send(
        self,
        dest_party: str,
        data: Any,
        upstream_seq_id: Any,
        downstream_seq_id: Any,
        stream: Optional[str] = None,
        round_tag: Optional[int] = None,
        epoch_tag: Optional[int] = None,
        quant_meta: Optional[Dict[str, Any]] = None,
        blob_offer: bool = False,
        version_tag: Optional[int] = None,
    ) -> LocalRef:
        """Owner-initiated push.  Returns a LocalRef resolving to True/False.

        Failures are swallowed into ``False`` + a log line (parity:
        ``barriers.py:244-248``); the cleanup watchdog turns persistent
        failures into process exit when configured.  Beyond parity, a
        failed producer task or encode also poisons the promised key on
        the consumer (see :meth:`_send_poison`).

        ``stream``: a stable stream name routes the push through the
        per-peer delta cache (only changed chunks cross the wire — see
        :meth:`TransportClient._send_stream`).

        ``round_tag``: federated round index stamped into the frame's
        metadata (``wire.ROUND_TAG_KEY``) — with pipelined rounds one
        round's frames are still in flight while the next computes, and
        the tag is what keeps receiver logs and the overlap runner's
        same-round fallback attributable to the round that owns them.

        ``epoch_tag``: roster epoch stamped into the frame metadata
        (``wire.EPOCH_TAG_KEY``) — a receiver whose roster has advanced
        rejects the frame loudly instead of parking stale bytes (see
        :class:`RosterState`).

        ``quant_meta``: compact shared-quantization-grid descriptor
        stamped into the frame metadata (``wire.QUANT_GRID_KEY``,
        JSON-encoded) when the payload is integer codes on the round's
        shared grid — see :mod:`rayfed_tpu.fl.quantize`.

        ``blob_offer``: let the object plane replace a large immutable
        payload with its fingerprint handle (pull-on-demand; see
        :meth:`send_many`).

        ``version_tag``: buffered-async MODEL VERSION stamped into the
        frame metadata (``wire.ASYNC_VERSION_KEY``) — broadcasts carry
        the version they publish, contributions the version they
        trained from, and the coordinator derives staleness from the
        pair (see :mod:`rayfed_tpu.fl.async_rounds`).
        """
        return self.send_many(
            [dest_party], data, upstream_seq_id, downstream_seq_id,
            stream=stream, round_tag=round_tag, epoch_tag=epoch_tag,
            quant_meta=quant_meta, blob_offer=blob_offer,
            version_tag=version_tag,
        )[dest_party]

    def send_many(
        self,
        dest_parties: Sequence[str],
        data: Any,
        upstream_seq_id: Any,
        downstream_seq_id: Any,
        stream: Optional[str] = None,
        round_tag: Optional[int] = None,
        epoch_tag: Optional[int] = None,
        quant_meta: Optional[Dict[str, Any]] = None,
        blob_offer: bool = False,
        version_tag: Optional[int] = None,
    ) -> Dict[str, LocalRef]:
        """Fan one value out to N parties — encode once, send concurrently.

        The broadcast-on-get path used to encode (and device→host fetch,
        and checksum) the same value once PER destination; here the
        payload buffers are built once, lazy shards are wrapped so the
        device fetch runs once (:func:`wire.share_buffers`), and the N
        ``send_data`` coroutines run concurrently on the loop — each
        connection's writev in its own executor thread, so fan-out wall
        time approaches max(per-dest wire time) instead of the sum.

        Returns ``{party: LocalRef→bool}`` (one result per destination,
        same swallow-to-False semantics as :meth:`send`).

        ``blob_offer=True`` (the ``fed.get`` broadcast path): when the
        resolved value is a large immutable object (a plain PackedTree
        at or above ``JobConfig.blob_broadcast_min_bytes``), the object
        plane publishes its wire bytes content-addressed and the frame
        carries the small fingerprint HANDLE instead of the payload
        (stamped ``wire.BLOB_HANDLE_KEY``); receivers resolve the
        handle lazily — a content-cache hit transfers zero payload
        bytes, a miss pulls from this party via BLOB_GET.  See
        :mod:`rayfed_tpu.transport.objectstore`.
        """
        dests = list(dest_parties)
        out_refs: Dict[str, LocalRef] = {p: LocalRef() for p in dests}
        self.stats["send_op_count"] += len(dests)
        send_meta: Optional[Dict[str, str]] = {}
        if round_tag is not None:
            send_meta[wire.ROUND_TAG_KEY] = str(round_tag)
        if epoch_tag is not None:
            send_meta[wire.EPOCH_TAG_KEY] = str(epoch_tag)
        if version_tag is not None:
            send_meta[wire.ASYNC_VERSION_KEY] = str(version_tag)
        if quant_meta is not None:
            import json as _json

            send_meta[wire.QUANT_GRID_KEY] = _json.dumps(
                quant_meta, separators=(",", ":"), sort_keys=True
            )
        send_meta = send_meta or None

        def _poison_all(exc: BaseException) -> None:
            for p in dests:
                poison_ref = self._send_poison(
                    p, upstream_seq_id, downstream_seq_id, exc
                )
                # False only after the poison delivery settles —
                # otherwise shutdown's task-cancel races the in-flight
                # poison send.
                poison_ref.add_done_callback(
                    lambda _ref, p=p: out_refs[p].set_result(False)
                )

        def _encode_and_send(value: Any) -> None:
            final_meta = send_meta
            try:
                if blob_offer:
                    handle = self.objects.maybe_offer(
                        value, self._job.blob_broadcast_min_bytes
                    )
                    if handle is not None:
                        # Fingerprint first: the frame ships the small
                        # handle; the payload moves only for receivers
                        # that miss the content cache (pull-on-demand).
                        value = handle
                        final_meta = dict(send_meta or {})
                        final_meta[wire.BLOB_HANDLE_KEY] = handle["fp"]
                t_enc0 = time.perf_counter()
                bufs = wire.encode_payload(value, lazy_shards=True)
                if len(dests) > 1:
                    bufs = wire.share_buffers(bufs)
                nbytes = wire.payload_nbytes(bufs)
                streaming = any(
                    isinstance(b, wire.LazyBuffer) for b in bufs
                ) or nbytes >= wire.SHARD_STREAM_THRESHOLD
                snapshot = None
                if stream is not None and len(dests) > 1:
                    # ONE contiguous snapshot + chunk-CRC pass (codec
                    # thread), shared by every destination's delta
                    # cache — the fan-out contract of this method.
                    # Single-destination stream sends skip it: the
                    # client snapshots into its reusable per-(dest,
                    # stream) send arena instead (zero per-round
                    # allocation, pipelined with the stripe frames).
                    snapshot = TransportClient.snapshot_stream_payload(
                        bufs
                    )
                self.stats["send_encode_s"] += time.perf_counter() - t_enc0
                crc = None
                if stream is None and not streaming and self._get_client(
                    dests[0]
                ).checksum_enabled:
                    # Small payloads: checksum once on the codec thread,
                    # shared by every destination.  Streamed payloads
                    # chain their CRC per chunk overlapped with the
                    # socket write (TransportClient._write_frame).
                    from rayfed_tpu import native

                    crc = native.crc32c_multi(bufs)
            except Exception as e:
                logger.warning("[%s] failed to encode payload for %s: %r",
                               self._party, dests, e)
                _poison_all(e)
                return

            def _dispatch_one(p: str) -> None:
                """One destination's write: client construction +
                coroutine scheduling, off the shared encode thread.

                These used to be issued sequentially after the shared
                encode/CRC pass — a slow client construction (TLS
                context, native warmup) or a long dispatch queue for
                destination k delayed the FIRST byte to destinations
                k+1..N.  Each destination now dispatches on its own
                executor slot, and its wall time (dispatch → ACK) is
                accounted per destination in ``get_stats()``.
                """
                t0 = time.perf_counter()
                try:
                    client = self._get_client(p)
                    # Coalesced wake: an N-way fan-out arms the loop
                    # once, not once per destination (local.py batcher).
                    cf = local.post_coroutine(
                        self._loop,
                        client.send_data(bufs, str(upstream_seq_id),
                                         str(downstream_seq_id), crc=crc,
                                         metadata=final_meta,
                                         stream=stream,
                                         stream_snapshot=snapshot),
                    )
                except Exception as e:  # pragma: no cover - construction
                    logger.warning(
                        "[%s] cannot send to %s (up=%s down=%s): %r",
                        self._party, p, upstream_seq_id, downstream_seq_id,
                        e,
                    )
                    out_refs[p].set_result(False)
                    return

                def _done(f):
                    dt = time.perf_counter() - t0
                    with self._dest_lock:
                        self._dest_seconds[p] = (
                            self._dest_seconds.get(p, 0.0) + dt
                        )
                        self._dest_ops[p] = self._dest_ops.get(p, 0) + 1
                    _tr = telemetry.active()
                    try:
                        f.result()
                        self._peers_acked.add(p)
                        self.stats["send_bytes"] += nbytes
                        self.stats["send_seconds"] += dt
                        self.transfer_log.record(
                            "send", p, upstream_seq_id,
                            downstream_seq_id, nbytes, dt,
                        )
                        if _tr is not None:
                            _tr.emit(
                                "wire.send", party=self._party, peer=p,
                                stream=stream, nbytes=nbytes,
                                t_start=time.time() - dt, dur_s=dt,
                                round=round_tag, epoch=epoch_tag,
                            )
                        out_refs[p].set_result(True)
                    except Exception as e:
                        logger.warning(
                            "[%s] failed to send to %s (up=%s down=%s%s): %r",
                            self._party, p, upstream_seq_id,
                            downstream_seq_id,
                            "" if round_tag is None
                            else f" round={round_tag}", e,
                        )
                        if _tr is not None:
                            _tr.emit(
                                "wire.send", party=self._party, peer=p,
                                stream=stream, nbytes=nbytes,
                                t_start=time.time() - dt, dur_s=dt,
                                round=round_tag, epoch=epoch_tag,
                                outcome="error",
                                detail={"error": repr(e)},
                            )
                        out_refs[p].set_result(False)

                cf.add_done_callback(_done)

            if len(dests) == 1:
                _dispatch_one(dests[0])  # no second hop for the 1:1 path
            else:
                for p in dests:
                    self._codec_pool.submit(_dispatch_one, p)

        if isinstance(data, LocalRef):
            def _on_data(ref: LocalRef) -> None:
                exc = ref.exception()
                if exc is not None:
                    logger.warning(
                        "[%s] upstream task failed; cannot send to %s: %r",
                        self._party, dests, exc,
                    )
                    _poison_all(exc)
                    return
                self._codec_pool.submit(_encode_and_send, ref.resolve())

            data.add_done_callback(_on_data)
        else:
            self._codec_pool.submit(_encode_and_send, data)
        return out_refs

    # -- recv path (RecvProxy role) ------------------------------------------

    def recv(
        self,
        src_party: str,
        upstream_seq_id: Any,
        downstream_seq_id: Any,
    ) -> LocalRef:
        """Park until the owner's push lands; resolves to the decoded value."""
        allowed = self._cluster.serializing_allowed_list
        device_put = self._job.device_put_received

        t_req = time.time()
        # post_coroutine, not run_coroutine_threadsafe: a round's worth
        # of parked recvs (N-1 in a hierarchy region) arms the loop once.
        cf = local.post_coroutine(
            self._loop,
            self._mailbox.get(
                str(upstream_seq_id),
                str(downstream_seq_id),
                # Backstop deadline: an abandoned recv surfaces as an
                # error instead of a parked coroutine leaking forever.
                timeout_s=self._job.recv_backstop_s,
                # Lets the health monitor fail exactly this waiter when
                # src_party dies (peer-death fail-fast).
                src_party=src_party,
            ),
        )
        # Delivery timestamp for the mailbox.wait span: _decode runs on
        # the codec pool AFTER a queue hop, so stamping inside it would
        # bill decode-pool backlog as "the peer had not pushed yet" —
        # exactly the misattribution the recorder exists to prevent.
        t_delivered: list = []
        if telemetry.active() is not None:
            cf.add_done_callback(lambda _f: t_delivered.append(time.time()))

        def _decode(message: Message) -> Any:
            _tr = telemetry.active()
            if _tr is not None:
                # The mailbox park (request → delivery) and the socket-
                # read wall are the receiver's two waits: the first is
                # "the peer had not pushed yet", the second "the bytes
                # were in flight".  Round/epoch attribution rides the
                # frame's own metadata tags.
                meta = message.metadata or {}
                rnd = meta.get(wire.ROUND_TAG_KEY)
                ep = meta.get(wire.EPOCH_TAG_KEY)
                # Buffered-async frames carry a model version instead
                # of a round tag — surface it as the round so the
                # flight recorder's per-round pages become per-version
                # pages with no schema change.
                if rnd is None:
                    rnd = meta.get(wire.ASYNC_VERSION_KEY)
                kw = dict(
                    party=self._party, peer=message.src_party,
                    stream=str(upstream_seq_id),
                    round=int(rnd) if rnd is not None else None,
                    epoch=int(ep) if ep is not None else None,
                    outcome="error" if message.error is not None else "ok",
                )
                now = t_delivered[0] if t_delivered else time.time()
                _tr.emit(
                    "mailbox.wait", t_start=t_req,
                    dur_s=max(0.0, now - t_req), **kw,
                )
                if message.error is None:
                    _tr.emit(
                        "wire.read",
                        t_start=now - float(message.read_seconds or 0.0),
                        dur_s=float(message.read_seconds or 0.0),
                        nbytes=len(message.payload), **kw,
                    )
            if message.error is not None:
                from rayfed_tpu.exceptions import RemoteError

                raise RemoteError.from_wire(message.error)
            mesh = self.mesh_provider() if self.mesh_provider else None
            value = wire.decode_payload(
                message.payload,
                allowed=allowed,
                device_put=device_put,
                mesh=mesh,
                zero_copy=self._job.zero_copy_host_arrays,
            )
            # Denominator = socket-read wall time (honest wire GB/s
            # at the receiver); decode runs here but is not billed.
            self.transfer_log.record(
                "recv", message.src_party, upstream_seq_id,
                downstream_seq_id, len(message.payload),
                message.read_seconds,
            )
            return value

        # Decode on the codec pool, never the event loop; a packed tree
        # (fl.compression.PackedTree) comes back as ONE zero-copy buffer
        # view + skeleton here — no per-leaf intermediate copies.
        return LocalRef(cf).then(_decode, executor=self._codec_pool)

    def recv_stream(
        self,
        src_party: str,
        upstream_seq_id: Any,
        downstream_seq_id: Any,
        sink: Any,
    ) -> None:
        """Chunk-granular receive: attach ``sink`` to one rendezvous.

        Instead of parking a recv and decoding the complete payload, the
        sink sees payload bytes AS THEY LAND on the wire
        (``on_bytes(view, total)`` from transport threads, then
        ``on_complete(payload)`` / ``on_error(err)``) — the hook the
        streaming aggregator builds on.  A push that raced in before
        registration is taken from the mailbox and delivered whole.  Do
        not also call :meth:`recv` on the same key.

        ``src_party`` enrolls the key with the health monitor: if the
        source dies mid-stream, the sink's ``on_error`` fires with the
        peer-death error instead of waiting out the recv backstop (the
        chunk-sink analogue of the mailbox's fail-fast).
        """
        self.recv_stream_many(
            [(src_party, upstream_seq_id, downstream_seq_id, sink)]
        )

    def recv_stream_many(self, entries: Sequence[tuple]) -> None:
        """Register many ``(src_party, up, down, sink)`` chunk sinks in
        ONE loop hop — the stripe demux of a ring round: a stripe
        owner's N-1 contribution sinks attach in a single scheduling
        round trip, so no early-arriving stripe pays an extra
        cross-thread latency per source.  Semantics per entry are
        exactly :meth:`recv_stream`."""
        prepared = [
            (str(src), (str(up), str(down)), sink)
            for src, up, down, sink in entries
        ]

        def _on_loop() -> None:
            for src, key, sink in prepared:
                msg = self._mailbox.try_take(key)
                if msg is not None:
                    try:
                        if msg.error is not None:
                            sink.on_error(msg.error)
                        else:
                            sink.on_complete(msg.payload)
                    except Exception:  # pragma: no cover - sink bug
                        logger.exception(
                            "[%s] stream sink failed on mailbox replay",
                            self._party,
                        )
                    continue
                err = self._mailbox.party_failure(src)
                if err is not None:
                    # The source was ALREADY declared dead (e.g. a ring
                    # fallback re-receiving from the peer that killed the
                    # ring round): fail the sink now, exactly like
                    # Mailbox.get fails a fresh recv on a dead party —
                    # the monitor only fires on the alive→dead
                    # transition, so a sink registered after it would
                    # otherwise park until the recv backstop.  Raced-in
                    # real data (above) is still preferred, like get's.
                    self._mailbox.stats["peer_failed_recvs"] += 1
                    try:
                        sink.on_error(err)
                    except Exception:  # pragma: no cover - sink bug
                        logger.exception(
                            "[%s] stream sink failed on dead-party "
                            "fast-fail", self._party,
                        )
                    continue
                self._server.register_chunk_sink(key, sink)
                self._stream_srcs[key] = src

        self._loop.call_soon_threadsafe(_on_loop)

    def cancel_stream(
        self, upstream_seq_id: Any, downstream_seq_id: Any
    ) -> None:
        """Detach a sink registered by :meth:`recv_stream` (timeout paths)."""
        key = (str(upstream_seq_id), str(downstream_seq_id))

        def _on_loop() -> None:
            self._server.unregister_chunk_sink(key)
            self._stream_srcs.pop(key, None)

        self._loop.call_soon_threadsafe(_on_loop)

    # -- elastic membership (roster control plane) ----------------------------

    def _observe_membership(self, message) -> bool:
        """Server observer (loop thread): membership requests — keys
        prefixed :data:`ROSTER_REQ_PREFIX` — go to the inbox, not the
        mailbox (the coordinator polls the inbox at round boundaries;
        a mailbox rendezvous would need the recv side to know the
        sender's nonce in advance)."""
        if not str(message.upstream_seq_id).startswith(ROSTER_REQ_PREFIX):
            return False
        if message.error is not None:
            return True  # a poisoned control key carries nothing to act on
        self._membership_inbox.append(message)
        return True

    def drain_membership_requests(self) -> list:
        """Decoded membership requests received since the last drain —
        each a dict like ``{"op": "join"|"leave", "party": ..., "nonce":
        ...}``.  Any thread; arrival order preserved."""
        out = []
        while True:
            try:
                msg = self._membership_inbox.popleft()
            except IndexError:
                break
            try:
                req = wire.decode_payload(
                    msg.payload,
                    allowed=self._cluster.serializing_allowed_list,
                    device_put=False,
                )
                if isinstance(req, dict):
                    out.append(req)
                else:
                    logger.warning(
                        "[%s] malformed membership request from %s: %r",
                        self._party, msg.src_party, type(req).__name__,
                    )
            except Exception:
                logger.exception(
                    "[%s] failed to decode membership request from %s",
                    self._party, msg.src_party,
                )
        return out

    def ring_neighbors(
        self, parties: Optional[Sequence[str]] = None,
        party: Optional[str] = None,
    ) -> tuple:
        """``(predecessor, successor)`` of ``party`` (default: this
        party) on the sorted ring of ``parties`` (default: the whole
        cluster) — see module-level :func:`ring_neighbors`."""
        return ring_neighbors(
            parties if parties is not None else list(self._cluster.parties),
            party or self._party,
        )

    # -- readiness ------------------------------------------------------------

    def ping(self, dest_party: str, timeout_s: float = 1.0) -> bool:
        cf = asyncio.run_coroutine_threadsafe(
            self._get_client(dest_party).ping(timeout_s), self._loop
        )
        try:
            return cf.result(timeout=timeout_s + 5)
        except Exception:
            return False

    def ensure_secagg_peer_keys(
        self, parties: Sequence[str], timeout_s: float = 30.0
    ) -> None:
        """Establish the pairwise secure-aggregation key state with
        every listed peer before the first masked round.

        Key agreement rides the connection HELLO (``wire.
        SECAGG_PUB_KEY``), so one successful ping per missing pair is
        enough: our HELLO hands the peer our key, its reply hands us
        its.  Peers whose keys are already recorded cost nothing.
        Raises :class:`~rayfed_tpu.transport.secagg.SecAggError` naming
        every peer still missing at the deadline — masks derived
        without the pair state could never cancel.
        """
        deadline = time.monotonic() + float(timeout_s)
        missing = [
            p for p in parties
            if p != self._party and not self.secagg_keys.has_peer(p)
        ]
        while missing:
            for p in list(missing):
                if self.ping(p, timeout_s=2.0) and (
                    self.secagg_keys.has_peer(p)
                ):
                    missing.remove(p)
            if not missing:
                return
            if time.monotonic() >= deadline:
                raise secagg_keys.SecAggError(
                    f"[{self._party}] no secure-aggregation key from "
                    f"{sorted(missing)} after {timeout_s:.0f}s — the "
                    f"peers are unreachable or run a build without the "
                    f"secagg HELLO advertisement"
                )
            time.sleep(0.2)

    # -- flight-recorder trace collection -------------------------------------

    _TRACE_REQ_PREFIX = "trace.req."
    _TRACE_REPLY_PREFIX = "trace.put."
    _TRACE_DOWN = "trace"

    def _observe_trace_request(self, message) -> bool:
        """Server observer (transport loop thread): TRACE_GET request
        frames — identified by their ``wire.TRACE_GET_KEY`` metadata —
        are consumed here (ACKed, never enter the mailbox) and served
        off-loop from the flight-recorder ring."""
        import json as _json

        raw = (message.metadata or {}).get(wire.TRACE_GET_KEY)
        if raw is None:
            return False
        if message.error is not None:
            return True  # a poisoned request carries nothing to serve
        try:
            req = telemetry.check_trace_request(_json.loads(raw))
        except Exception as exc:
            logger.warning(
                "[%s] malformed TRACE_GET request from %s: %r",
                self._party, message.src_party, raw,
            )
            # Best-effort error reply: a silent consume would leave the
            # collector parked for its FULL per-peer timeout (a
            # version-skewed peer is exactly when you want the reason
            # fast).  Only possible when the reply key survived the
            # parse failure.
            rk = None
            try:
                maybe = _json.loads(raw)
                if isinstance(maybe, dict) and isinstance(
                    maybe.get("rk"), str
                ):
                    rk = maybe["rk"]
            except Exception:
                pass
            if rk is not None:
                self._codec_pool.submit(
                    self._serve_trace_error, message.src_party, rk,
                    f"malformed trace request: {exc!r}",
                )
            return True
        self._codec_pool.submit(self._serve_trace, message.src_party, req)
        return True

    def _serve_trace_error(
        self, requester: str, reply_key: str, err: str,
    ) -> None:
        """Codec-pool thread: push an err-marked empty reply so the
        collector fails fast instead of waiting out its timeout."""
        rep = telemetry.make_trace_reply_meta(
            self._party, 0, armed=telemetry.installed() is not None,
            err=err,
        )
        self._push_trace_reply(
            requester, reply_key, telemetry.encode_records([]), rep,
        )

    def _serve_trace(self, requester: str, req: Dict[str, Any]) -> None:
        """Codec-pool thread: push this party's ring window (or an
        empty, armed=False-marked window when the recorder is disarmed)
        to the requester's reply key."""
        try:
            rec = telemetry.installed()
            rounds = req["rnd"]
            if rec is not None:
                window = [
                    r for r in rec.records(
                        rounds=None if rounds is None else tuple(rounds)
                    )
                    if r.party is None or r.party == self._party
                ]
            else:
                window = []
            payload = telemetry.encode_records(window)
            rep = telemetry.make_trace_reply_meta(
                self._party, len(window), armed=rec is not None
            )
        except Exception as exc:
            logger.exception(
                "[%s] trace window for %s could not be built",
                self._party, requester,
            )
            self._serve_trace_error(
                requester, req["rk"], f"trace serve failed: {exc!r}"
            )
            return
        self._push_trace_reply(requester, req["rk"], payload, rep)

    def _push_trace_reply(
        self, requester: str, reply_key: str, payload: bytes,
        rep: Dict[str, Any],
    ) -> None:
        import json as _json

        metadata = {
            wire.TRACE_PUT_KEY: _json.dumps(
                rep, separators=(",", ":"), sort_keys=True
            )
        }
        try:
            client = self._get_client(requester)
            cf = asyncio.run_coroutine_threadsafe(
                client.send_data(
                    [payload], reply_key, self._TRACE_DOWN,
                    metadata=metadata,
                ),
                self._loop,
            )
        except Exception:
            logger.exception(
                "[%s] trace serve to %s could not be dispatched",
                self._party, requester,
            )
            return

        def _done(f) -> None:
            exc = (
                f.exception() if not f.cancelled()
                else asyncio.CancelledError("transport stopped")
            )
            if exc is not None:
                # Best-effort: the collector's per-peer timeout governs.
                logger.warning(
                    "[%s] trace serve to %s failed: %r",
                    self._party, requester, exc,
                )

        cf.add_done_callback(_done)

    def discard_empty_park(self, upstream: Any, downstream: Any) -> None:
        """Loop-side cleanup for a CANCELLED rendezvous park (trace
        pulls, object-plane pulls): a cancelled ``Mailbox.get`` would
        otherwise leave an empty entry whose ``expected_src`` keeps the
        health monitor pinging the peer forever.  Raced-in real data
        (message present) is left for the TTL gc.  ONE copy of the
        entry-semantics poke — the two pull protocols must not diverge
        on it."""
        key = (str(upstream), str(downstream))

        def _discard() -> None:
            entry = self._mailbox._entries.get(key)
            if entry is not None and entry.message is None:
                self._mailbox._entries.pop(key, None)

        self._loop.call_soon_threadsafe(_discard)

    def collect_trace(
        self, peer: str, rounds: Any = None,
        timeout_s: Optional[float] = None,
    ) -> tuple:
        """One TRACE_GET round trip against one peer: returns
        ``(records, clock_offset, reply_meta)``.

        The reply wait parks in the mailbox WITH the peer named
        (``src_party``), so a monitor-declared-dead peer fails the
        collection leg immediately instead of waiting out the timeout.
        The round trip doubles as the clock-offset sample: the request
        stamps our wall clock at send, the reply stamps the peer's at
        serve, and :func:`telemetry.estimate_clock_offset` bounds the
        error at RTT/2.
        """
        import json as _json
        import uuid as _uuid

        timeout = (
            float(timeout_s) if timeout_s is not None
            else float(self._job.cross_silo_timeout_s)
        )
        nonce = _uuid.uuid4().hex
        reply_up = f"{self._TRACE_REPLY_PREFIX}{self._party}.{nonce}"
        recv_cf = asyncio.run_coroutine_threadsafe(
            self._mailbox.get(
                reply_up, self._TRACE_DOWN, timeout_s=timeout,
                src_party=peer,
            ),
            self._loop,
        )
        t_send = time.time()
        req = telemetry.make_trace_request(
            reply_up, rounds=rounds, t_send=t_send
        )
        metadata = {
            wire.TRACE_GET_KEY: _json.dumps(
                req, separators=(",", ":"), sort_keys=True
            )
        }
        try:
            client = self._get_client(peer)
            send_cf = asyncio.run_coroutine_threadsafe(
                client.send_data(
                    [], f"{self._TRACE_REQ_PREFIX}{self._party}.{nonce}",
                    self._TRACE_DOWN, metadata=metadata,
                ),
                self._loop,
            )
            send_cf.result(timeout=timeout)
        except Exception as exc:
            recv_cf.cancel()
            self.discard_empty_park(reply_up, self._TRACE_DOWN)
            raise telemetry.TelemetryError(
                f"trace request to {peer!r} could not be delivered: "
                f"{exc!r}"
            ) from exc
        try:
            msg = recv_cf.result(timeout=timeout + 5)
        except Exception as exc:
            raise telemetry.TelemetryError(
                f"no trace reply from {peer!r} within {timeout}s: {exc!r}"
            ) from exc
        t_recv = time.time()
        if msg.error is not None:
            raise telemetry.TelemetryError(
                f"trace collection from {peer!r} failed: "
                f"{msg.error.get('msg', msg.error)}"
            )
        raw_rep = (msg.metadata or {}).get(wire.TRACE_PUT_KEY)
        if raw_rep is None:
            raise telemetry.TelemetryError(
                f"trace reply from {peer!r} carries no "
                f"{wire.TRACE_PUT_KEY!r} metadata"
            )
        rep = telemetry.check_trace_reply_meta(_json.loads(raw_rep))
        if rep["err"]:
            raise telemetry.TelemetryError(
                f"{peer!r} could not serve its trace window: {rep['err']}"
            )
        records = telemetry.decode_records(msg.payload)
        offset = telemetry.estimate_clock_offset(t_send, t_recv, rep["tw"])
        return records, offset, rep

    def get_stats(self) -> Dict[str, Any]:
        stats = dict(self.stats)
        stats.update(self._server.stats)
        stats.update(self._mailbox.stats)  # dups, expiries, peer fails
        stats["pending_recvs"] = self._mailbox.pending_count()
        # Send-pipeline decomposition summed over per-peer clients:
        # prepare (device→host fetch + checksum) + write > frame wall
        # means the chunk pipeline overlapped them; the saved seconds
        # are the overlap win vs a serialized encode→checksum→write.
        with self._clients_lock:
            clients = list(self._clients.values())
        for key in (
            "send_frames", "send_payload_bytes", "send_prepare_s",
            "send_write_s", "send_frame_wall_s",
            "delta_stream_frames", "delta_full_frames",
            "delta_logical_bytes", "delta_wire_bytes",
            "send_d2h_s", "send_copy_s", "send_crc_s",
            "send_loop_wait_s", "send_socket_s",
            "send_striped_payloads", "send_stripe_frames",
        ):
            stats[key] = sum(c.stats[key] for c in clients)
        # Send-path stage breakdown (ISSUE 5's can't-silently-reopen
        # telemetry): where every second between "payload ready" and
        # "bytes on the wire" went.  encode = pytree→wire buffers
        # (codec pool) + arena/gather copies; d2h = device→host
        # fetches; crc = all checksum passes; loop_wait = produced
        # chunks waiting for a rail/loop slot; socket = writev/drain.
        stats["send_path_breakdown_ms"] = {
            "encode_ms": round(
                (stats["send_encode_s"] + stats["send_copy_s"]) * 1e3, 2
            ),
            "d2h_ms": round(stats["send_d2h_s"] * 1e3, 2),
            "crc_ms": round(stats["send_crc_s"] * 1e3, 2),
            "loop_wait_ms": round(stats["send_loop_wait_s"] * 1e3, 2),
            "socket_ms": round(stats["send_socket_s"] * 1e3, 2),
        }
        # Same stages split per transport backend (local-link fast
        # path): the tcp/uds/shm rows sum to the totals above minus the
        # codec-pool encode (which runs before the backend is chosen),
        # so a local-link regression is attributable from metrics
        # alone.  For shm, socket_ms is the handoff→ACK wait.
        stats["send_path_breakdown_by_backend_ms"] = {
            b: {
                "encode_ms": round(
                    sum(c.stats[f"send_copy_s_{b}"] for c in clients) * 1e3,
                    2,
                ),
                "d2h_ms": round(
                    sum(c.stats[f"send_d2h_s_{b}"] for c in clients) * 1e3, 2
                ),
                "crc_ms": round(
                    sum(c.stats[f"send_crc_s_{b}"] for c in clients) * 1e3, 2
                ),
                "loop_wait_ms": round(
                    sum(c.stats[f"send_loop_wait_s_{b}"] for c in clients)
                    * 1e3,
                    2,
                ),
                "socket_ms": round(
                    sum(c.stats[f"send_socket_s_{b}"] for c in clients)
                    * 1e3,
                    2,
                ),
            }
            for b in ("tcp", "uds", "shm")
        }
        # Fraction of stream-send logical bytes the delta cache kept off
        # the wire (0.0 when no stream sends happened).
        logical = stats["delta_logical_bytes"]
        stats["delta_bytes_saved_frac"] = (
            (logical - stats["delta_wire_bytes"]) / logical
            if logical > 0
            else 0.0
        )
        stats["send_overlap_saved_s"] = max(
            0.0,
            stats["send_prepare_s"] + stats["send_write_s"]
            - stats["send_frame_wall_s"],
        )
        # Per-destination send wall (dispatch → ACK), cumulative: the
        # fan-out / ring hop diagnostic — which peer does this party
        # actually wait on.  Snapshots, not the live dicts (mutated
        # from send callbacks).
        with self._dest_lock:
            stats["send_dest_seconds"] = dict(self._dest_seconds)
            stats["send_dest_ops"] = dict(self._dest_ops)
        # Snapshot, not the live dict: get_stats runs on user threads
        # while the loop-thread health monitor mutates the dead set.
        stats["dead_parties"] = sorted(self._mailbox.dead_parties_snapshot())
        # Secure-aggregation key-agreement state: this party's suite and
        # which peers have completed the HELLO key exchange (the
        # operator's "why can't these two mask" diagnostic).
        stats["secagg"] = self.secagg_keys.describe()
        # Content-addressed object plane: cache hit/miss, pull/serve and
        # eviction counters (the "did the handle actually save bytes"
        # diagnostic — also what the rejoin bench gates read).
        stats["object_plane"] = self.objects.stats_snapshot()
        # Flight recorder: ring occupancy/drop counters when armed (the
        # "is my trace window still complete" diagnostic), a loud
        # armed=False marker otherwise.
        rec = telemetry.installed()
        stats["telemetry"] = (
            rec.stats() if rec is not None else {"trace_armed": False}
        )
        return stats
