"""Wire format: frames + zero-copy tensor payload codec.

The reference ships ``cloudpickle.dumps(data)`` of whole Python objects
(``barriers.py:151``) — for device arrays that means device→host copy,
pickle memcpy, and a pickle parse on the far side.  Here array leaves
travel as **raw buffers** described by a small JSON manifest: the receiver
reconstructs ndarrays with ``np.frombuffer`` (zero-copy) and can
``jax.device_put`` them directly, optionally with a target sharding.
Non-array leaves fall back to (allowlist-restricted) pickle per skeleton.

Frame layout (all integers big-endian)::

    magic   4s   b"RFW1"
    type    u8   DATA=1 ACK=2 PING=3 PONG=4 ERR=5
    flags   u8
    hlen    u32  header (JSON) length
    plen    u64  payload length
    header  hlen bytes of JSON
    payload plen bytes

Header fields: ``rid`` (request id for ACK matching), ``src`` party,
``up``/``down`` rendezvous seq ids, ``meta`` metadata headers.
"""

from __future__ import annotations

import functools
import json
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

try:  # registers 'bfloat16' & friends as numpy dtypes (jax dependency)
    import ml_dtypes  # noqa: F401
except ImportError:  # pragma: no cover
    pass

from rayfed_tpu import serialization
from rayfed_tpu import tree_util

MAGIC = b"RFW1"
_HEADER_STRUCT = struct.Struct(">4sBBIQ")
HEADER_SIZE = _HEADER_STRUCT.size

# Version of the payload manifest layout.  BUMP THIS whenever the
# manifest schema changes (new leaf kinds, renamed/removed fields,
# different framing of the skeleton) — ``tool/check_wire_format.py``
# (run by test.sh) fails the build when the layout fingerprint drifts
# without a version bump.  Receivers reject payloads from a NEWER
# format than they understand instead of misparsing them, and — since
# v4 — every connection opens with a HELLO handshake carrying this
# version, so two parties on different builds fail with a clean
# ProtocolMismatchError naming both versions instead of a confusing
# manifest-decode error mid-payload.
# History: 1 = unversioned original; 2 = "v" field added to manifest;
# 3 = stream/delta frames ("stm"/"ccsz"/"ccrc"/"dlt" header fields:
# per-chunk CRCs + changed-chunk bitmap manifest for per-peer delta
# sends — see make_delta_manifest); 4 = connection HELLO handshake
# (MSG_HELLO + "ver"), multi-rail stripe frames ("stp" marker, "dlt"
# with optional "bfp": a large payload's chunks fan out round-robin
# across the per-destination connection pool as per-chunk frames and
# are reassembled by (stream, chunk index) on the receiver).
WIRE_FORMAT_VERSION = 4

MSG_DATA = 1
MSG_ACK = 2
MSG_PING = 3
MSG_PONG = 4
MSG_ERR = 5
# Connection handshake (v4): the first frame a client sends on every
# new connection, header {"ver": WIRE_FORMAT_VERSION, "src": party}.
# The server replies MSG_HELLO {"ver": ...} on match, or a fatal
# MSG_ERR code="protocol" naming both versions on mismatch.
MSG_HELLO = 6

# Frame flag: a 4-byte CRC32-C trailer follows the payload (streamed
# sends compute the checksum incrementally, so it can't ride the header).
FLAG_CRC_TRAILER = 0x01

# Device arrays at or above this size are encoded per shard and fetched
# lazily, so the send path can overlap device→host fetch of shard k+1
# with the socket write of shard k.
SHARD_STREAM_THRESHOLD = 8 * 1024 * 1024

# With zero_copy decode, plain "nd" leaves at or above this size come
# back as READONLY views aliasing the payload (e.g. a packed-tree
# buffer just under the shard-stream threshold).  Smaller leaves keep
# the writable-copy behavior: a retained few-KB view must not pin a
# multi-GB payload buffer alive, and in-place consumers of small
# host leaves keep working.
ND_ZERO_COPY_MIN_BYTES = 1 * 1024 * 1024

# Granularity of stream/delta frames (wire v3): per-peer delta caches
# diff and ship the payload in chunks of this size, and per-chunk CRCs
# cover exactly these ranges.  Matches the client's WRITE_CHUNK_BYTES so
# a shipped chunk is one writev unit.
DELTA_CHUNK_BYTES = 4 * 1024 * 1024

# Payloads at or above this size ship as per-chunk stripe frames (wire
# v4) fanned round-robin across the per-destination connection pool:
# chunk k is on a socket while chunk k+1 is still being fetched from
# device and CRC'd — no full-payload serialization barrier — and the
# receiver reassembles by (stream, chunk index) with the delta-bitmap
# machinery.  Below it — or when fewer than 2 rails are available
# (client._default_stripe_rails: striping needs spare cores to pay for
# the per-frame ACKs and the receiver's reassembly memcpy) — the
# single-frame paths (cheaper per-payload header/ACK overhead, zero-
# copy delivery) are kept.
STRIPE_MIN_BYTES = 8 * 1024 * 1024

# Metadata key stamping a DATA frame with the federated round it belongs
# to (pipelined rounds keep one round's aggregation in flight under the
# next round's compute — the tag is what lets a receiver's logs and the
# runner's fallback attribute a late or failed frame to the ROUND that
# owns it, rather than silently folding it into whichever round is
# current).  Rides the ordinary per-send metadata dict inside the JSON
# header's "meta" field: no frame-layout change, but the key name is a
# cross-party contract — fingerprinted by tool/check_wire_format.py.
ROUND_TAG_KEY = "rnd"

# Metadata key carrying the sender's ROSTER EPOCH (elastic membership):
# quorum-round frames are stamped with the epoch their sender's roster
# was at, and a receiver whose roster has advanced PAST the frame's
# epoch rejects it loudly (a fatal MSG_ERR naming both epochs) instead
# of parking a stale round's bytes in the mailbox forever.  Frames from
# a NEWER epoch are accepted — the advanced coordinator's broadcast is
# what carries the roster transition to lagging stragglers.  Late
# contributions are never lost by the rejection — they fold into the
# NEXT round via the sender's own local DGA correction, not via the
# stale wire push.  Same
# meta-dict transport as ROUND_TAG_KEY: no frame-layout change, but the
# key name is a cross-party contract — fingerprinted by
# tool/check_wire_format.py.
EPOCH_TAG_KEY = "ep"

# Metadata key carrying the round's shared QUANTIZATION-GRID descriptor
# (compressed-domain aggregation, fl.quantize): frames whose payload is
# integer codes on the round's shared grid are stamped with the compact
# JSON descriptor produced by ``fl.quantize.grid_descriptor`` —
# {version, fingerprint, block count, chunk elems, total elems, wire
# dtype} — so receivers and logs can attribute the frame to its grid
# without decoding the payload, and a cross-grid push is diagnosable at
# the transport layer (the fold layer independently re-verifies the
# fingerprint before any rescale).  Same meta-dict transport as
# ROUND_TAG_KEY: no frame-layout change, but the key name AND the
# descriptor schema are cross-party contracts — both fingerprinted by
# tool/check_wire_format.py.
QUANT_GRID_KEY = "qg"

# Metadata key carrying the coordinator's MODEL VERSION for buffered
# asynchronous rounds (fl.async_rounds): async broadcasts are stamped
# with the version they publish, and async contributions with the
# version of the broadcast they trained FROM — the coordinator derives
# each arrival's staleness as (current_version - trained_from) and a
# version-stale contribution against a rotated grid re-codes through
# the shared RoundCodec instead of folding garbage.  Same meta-dict
# transport as ROUND_TAG_KEY (the synchronous loops' round index plays
# this role there): no frame-layout change, but the key name is a
# cross-party contract — fingerprinted by tool/check_wire_format.py.
ASYNC_VERSION_KEY = "av"

# Content-addressed object plane (transport/objectstore.py): the
# repo's FIRST pull direction.  Three frame-metadata keys, all riding
# the ordinary per-send "meta" dict — NO frame-layout change, but the
# key names AND the JSON value schemas (single producers in
# rayfed_tpu/objects.py) are cross-party contracts, fingerprinted by
# tool/check_wire_format.py together with OBJECT_PLANE_VERSION.
#
# BLOB_GET_KEY — a pull REQUEST frame (tiny, empty payload): the
# requester asks a holder for the blob whose content fingerprint it
# was handed, naming the reply rendezvous key the requester is already
# parked on.  Value: ``objects.make_blob_request`` JSON.
BLOB_GET_KEY = "bget"
# BLOB_PUT_KEY — the pull REPLY frame: the holder pushes the stored
# wire bytes to the requester's reply key (ordinary DATA framing, so
# per-chunk CRCs, multi-rail striping and the stripe reassembly all
# apply unchanged), or a payload-less miss notice so the requester
# fails over to the next named holder instead of waiting out the
# backstop.  Value: ``objects.make_blob_reply_meta`` JSON.
BLOB_PUT_KEY = "bput"
# BLOB_HANDLE_KEY — stamped on a frame whose PAYLOAD is a blob handle
# offered in place of the object it names (fed.get broadcast of large
# immutable objects sends the fingerprint first; receivers with a
# content cache hit never transfer the payload at all).  Value: the
# bare fingerprint string — receiver logs can attribute the offer
# without decoding.
BLOB_HANDLE_KEY = "bhd"

# Federated flight recorder (rayfed_tpu/telemetry.py): cross-party
# trace collection rides the SAME request/reply shape as the object
# plane's BLOB_GET — a tiny payload-less request frame consumed by a
# server observer, answered by an ordinary DATA push onto a per-pull
# nonce reply key the requester is already parked on.  Two
# frame-metadata keys on the ordinary per-send "meta" dict — NO
# frame-layout change, but the key names AND the JSON value schemas
# (single producers ``telemetry.make_trace_request`` /
# ``make_trace_reply_meta``) are cross-party contracts, fingerprinted
# by tool/check_wire_format.py together with TELEMETRY_VERSION.
#
# TRACE_GET_KEY — the collection REQUEST: asks a peer for its flight-
# recorder ring window (optionally round-bounded), naming the reply
# rendezvous key and carrying the requester's wall-clock send stamp
# (one half of the NTP-style clock-offset estimate).
TRACE_GET_KEY = "tget"
# TRACE_PUT_KEY — the collection REPLY metadata: the serving party, its
# record count, its wall clock at serve time (the offset estimate's
# peer sample) and whether its recorder was armed.  The payload is the
# JSON-encoded record window (``telemetry.encode_records``).
TRACE_PUT_KEY = "tput"


def blob_fingerprint(data) -> str:
    """Content fingerprint of a serialized payload — THE single
    producer for the object plane's handles (``rayfed_tpu/objects.py``)
    and for checkpoint metadata stamps.

    Built ON the delta-cache's base-fingerprint machinery rather than
    beside it: the first field is exactly
    ``crc_fingerprint(chunk_crcs(data))`` — the same per-chunk-CRC word
    the per-peer delta cache maintains for its ``bfp`` frames — so a
    stored blob is directly cross-checkable against delta-cache state,
    and the chunk-CRC pass is shared work.  A sha256 tail makes the
    handle collision-resistant as a content ADDRESS (32-bit CRC words
    alone are fine for desync detection but not for skipping a
    transfer on fingerprint equality).
    """
    import hashlib

    mv = memoryview(data)
    if mv.format != "B":
        mv = mv.cast("B")
    base = crc_fingerprint(chunk_crcs(mv))
    strong = hashlib.sha256(mv).hexdigest()[:24]
    return f"b1.{base:08x}.{len(mv):x}.{strong}"


# Header key of the connection HELLO handshake carrying the sender's
# SECURE-AGGREGATION key advertisement (transport/secagg.py): a compact
# ``"<version>.<kex>.<prg>.<hex key>"`` string — an ephemeral X25519
# public key (or the stdlib fallback's per-session nonce) plus the mask
# PRG suite.  The client publishes its value in the HELLO it opens every
# connection with, the server records it and replies with its own, so
# ONE ping per pair establishes the pairwise mask-seed state in both
# directions with zero extra round trips and zero payload bytes (masks
# are generated from derived seeds, never transmitted —
# fl/secagg.py).  Absent on builds that never enable secure
# aggregation is fine: the value is opportunistic, and the loud failure
# lives at mask time.  Rides the HELLO header beside ``ver``/``src`` —
# NO frame-layout change, but the key name AND the value format version
# (``transport.secagg.SECAGG_VERSION``) are cross-party contracts,
# fingerprinted by tool/check_wire_format.py.
SECAGG_PUB_KEY = "sapk"

# Local-link colocation advertisement (transport/local.py) — three HELLO
# header keys the server volunteers on every handshake so a client can
# prove colocation and upgrade the link off TCP.  No frame-layout
# change: like SECAGG_PUB_KEY these ride the existing HELLO header, but
# the key names (and the identity semantics behind them) are
# cross-party contracts fingerprinted by tool/check_wire_format.py.
#
# LOCAL_HOST_KEY — the server host's boot-scoped identity fingerprint
# (``local.host_identity``: machine-id + boot-id hash).  A client whose
# own fingerprint matches has PROVED both ends share a kernel, which is
# what makes the advertised AF_UNIX path dialable and the CRC elision
# trustworthy (the bytes never leave the machine).
LOCAL_HOST_KEY = "lh"
# LOCAL_UDS_KEY — filesystem path of the server's AF_UNIX twin listener
# (same frame parser, same wire lock; absent when the listener could
# not be created).  Only meaningful when LOCAL_HOST_KEY matched: a path
# from a different host (or an unshared mount namespace) simply fails
# to connect, which the client treats as a loud fall-back to TCP.
LOCAL_UDS_KEY = "lu"
# LOCAL_TOKEN_KEY — the server PROCESS's random boot token
# (``local.process_token``): equality with the client's own token
# proves same-process (in-process virtual parties), unlocking the
# shared-memory handoff that skips sockets entirely.
LOCAL_TOKEN_KEY = "lt"


def pack_frame(
    msg_type: int,
    header: Dict[str, Any],
    payload: bytes = b"",
    payload_len: Optional[int] = None,
    flags: int = 0,
) -> List:
    """Returns a list of buffers to write (avoids concatenating the payload).

    ``payload_len`` lets a caller declare the length of payload buffers it
    will write itself (vectored sends) — this is the single producer of
    frame prefixes for both client and server.
    """
    hdr = json.dumps(header, separators=(",", ":")).encode()
    plen = payload_len if payload_len is not None else len(payload)
    prefix = _HEADER_STRUCT.pack(MAGIC, msg_type, flags, len(hdr), plen)
    out = [prefix, hdr]
    if payload:
        out.append(payload)
    return out


def unpack_frame_prefix(prefix: bytes) -> Tuple[int, int, int, int]:
    magic, msg_type, flags, hlen, plen = _HEADER_STRUCT.unpack(prefix)
    if magic != MAGIC:
        raise ValueError(f"bad frame magic {magic!r}")
    return msg_type, flags, hlen, plen


# ---------------------------------------------------------------------------
# Tensor payload codec
# ---------------------------------------------------------------------------


class _LeafSlot:
    """Placeholder for a leaf inside the pickled container skeleton."""

    __slots__ = ("index",)

    def __init__(self, index: int) -> None:
        self.index = index

    def __reduce__(self):
        return (_LeafSlot, (self.index,))


class _Skeleton:
    """Wrapper marking the pickled skeleton object."""

    __slots__ = ("tree",)

    def __init__(self, tree: Any) -> None:
        self.tree = tree

    def __reduce__(self):
        return (_Skeleton, (self.tree,))


def _is_array_leaf(x: Any) -> bool:
    return isinstance(x, (np.ndarray, jax.Array))


class LazyBuffer:
    """A payload buffer produced on demand (device→host fetch deferred).

    The streaming send path calls :meth:`produce` for shard k+1 while
    shard k is still being written to the socket, overlapping the fetch
    with the wire.  ``nbytes`` is known up front (from shard metadata) so
    the frame length can be declared before any fetch happens.
    """

    __slots__ = ("_produce", "nbytes")

    def __init__(self, produce, nbytes: int) -> None:
        self._produce = produce
        self.nbytes = nbytes

    def produce(self) -> memoryview:
        buf = self._produce()
        if buf.nbytes != self.nbytes:  # pragma: no cover - internal invariant
            raise ValueError(
                f"lazy buffer produced {buf.nbytes} bytes, declared {self.nbytes}"
            )
        return buf


class SharedLazyBuffer(LazyBuffer):
    """A LazyBuffer whose produce runs once and is shared by N readers.

    Fan-out sends push the SAME payload to several parties; without
    sharing, each destination's write path would repeat the device→host
    fetch.  The cached view lives until the last send drops the buffer
    list.
    """

    __slots__ = ("_lock", "_cached")

    def __init__(self, inner: LazyBuffer) -> None:
        super().__init__(inner._produce, inner.nbytes)
        self._lock = threading.Lock()
        self._cached: Optional[memoryview] = None

    def produce(self) -> memoryview:
        with self._lock:
            if self._cached is None:
                self._cached = super().produce()
            return self._cached


def share_buffers(buffers: List) -> List:
    """Wrap every LazyBuffer for one-fetch fan-out (see SharedLazyBuffer)."""
    return [
        SharedLazyBuffer(b) if isinstance(b, LazyBuffer) else b
        for b in buffers
    ]


def _shard_host_view(shard) -> memoryview:
    host = np.asarray(shard.data)
    if not host.flags["C_CONTIGUOUS"]:
        host = np.ascontiguousarray(host)
    return _array_buffer(host)


def _sharding_desc(arr: jax.Array) -> Optional[Dict[str, Any]]:
    """Portable description of a NamedSharding (axis sizes + spec)."""
    sh = arr.sharding
    if not isinstance(sh, jax.sharding.NamedSharding):
        return None
    entries: List[Any] = []
    for entry in sh.spec:
        if entry is None:
            entries.append(None)
        elif isinstance(entry, (tuple, list)):
            entries.append([str(a) for a in entry])
        else:
            entries.append([str(entry)])
    return {
        "axes": [
            [str(n), int(s)]
            for n, s in zip(sh.mesh.axis_names, sh.mesh.devices.shape)
        ],
        "spec": entries,
    }


def resolve_sharding(desc: Optional[Dict[str, Any]], mesh) -> Optional[Any]:
    """Rebuild a NamedSharding on the *receiver's* mesh from a wire desc.

    Only when the local mesh carries every axis the sender's spec uses,
    at the same size — otherwise None (caller falls back to a plain
    device_put)."""
    if not desc or mesh is None:
        return None
    local_axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    used = [a for entry in desc["spec"] if entry for a in entry]
    sender_axes = dict((n, s) for n, s in desc["axes"])
    for axis in used:
        if local_axes.get(axis) != sender_axes.get(axis):
            return None
    from jax.sharding import NamedSharding, PartitionSpec

    # Singleton axis lists unwrap to the bare name: PartitionSpec('dp')
    # and PartitionSpec(('dp',)) are equivalent but only compare equal on
    # newer jax — emit the canonical form.
    spec = PartitionSpec(
        *((tuple(e) if len(e) > 1 else e[0]) if e else None for e in desc["spec"])
    )
    return NamedSharding(mesh, spec)


def _encode_sharded_leaf(leaf: jax.Array, manifest_leaves: List, buffers: List):
    """Encode a large device array as per-shard lazy buffers."""
    dtype = np.dtype(leaf.dtype)
    shape = leaf.shape
    unique: Dict[tuple, Any] = {}
    for shard in leaf.addressable_shards:
        key = tuple(
            (sl.start or 0, sl.stop if sl.stop is not None else dim)
            for sl, dim in zip(shard.index, shape)
        )
        if key not in unique:  # drop replicas — one copy per region
            unique[key] = shard
    entries = []
    for key in sorted(unique):
        shard = unique[key]
        extents = [e - s for s, e in key]
        import math as _math

        nbytes = _math.prod(extents) * dtype.itemsize if extents else dtype.itemsize
        entries.append({"idx": [[s, e] for s, e in key], "n": nbytes})
        buffers.append(
            LazyBuffer(functools.partial(_shard_host_view, shard), nbytes)
        )
    manifest_leaves.append(
        {
            "k": "nds",
            "dtype": dtype.name,
            "shape": list(shape),
            "spec": _sharding_desc(leaf),
            "shards": entries,
        }
    )


def _array_buffer(host: np.ndarray) -> memoryview:
    """Zero-copy byte view; handles dtypes outside the buffer protocol (bf16, fp8)."""
    try:
        return memoryview(host).cast("B")
    except (ValueError, TypeError):
        return memoryview(host.reshape(-1).view(np.uint8))


def encode_payload(obj: Any, lazy_shards: bool = False) -> List:
    """Encode a pytree into wire buffers: ``[u32 manifest_len, manifest, *bufs]``.

    Array leaves (``jax.Array`` / ``np.ndarray``) become raw buffers; jax
    arrays are fetched to host once (``device_get``).  Everything else —
    including the container skeleton — is pickled.  Returns a list of
    buffers suitable for vectored writes (no large concatenation).

    With ``lazy_shards=True``, device arrays >= SHARD_STREAM_THRESHOLD
    are encoded per shard as :class:`LazyBuffer`s (manifest carries the
    shard index map + the sender's sharding), letting the streaming send
    path overlap device→host fetches with socket writes and the receiver
    re-shard without a host round trip through one giant buffer.
    """
    leaves, treedef = tree_util.tree_flatten(obj)
    manifest_leaves: List[Dict[str, Any]] = []
    buffers: List = []
    for leaf in leaves:
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            raise ValueError(
                f"cannot encode a non-fully-addressable global array "
                f"(shape {leaf.shape}) for a cross-party push: this "
                f"process only holds its local shards.  Gather it onto "
                f"the party's processes first (e.g. jax.experimental."
                f"multihost_utils.process_allgather) or push per-process "
                f"shards"
            )
        if (
            lazy_shards
            and isinstance(leaf, jax.Array)
            and leaf.nbytes >= SHARD_STREAM_THRESHOLD
            and leaf.is_fully_addressable
            and leaf.shape  # 0-d can't be sharded
        ):
            _encode_sharded_leaf(leaf, manifest_leaves, buffers)
        elif isinstance(leaf, jax.Array):
            host = np.asarray(jax.device_get(leaf))
            if not host.flags["C_CONTIGUOUS"]:
                # NB: np.ascontiguousarray promotes 0-d to (1,) — only
                # call it when actually needed (0-d is always contiguous).
                host = np.ascontiguousarray(host)
            manifest_leaves.append(
                {
                    "k": "nd",
                    "dtype": host.dtype.name,
                    "shape": list(host.shape),
                    "n": host.nbytes,
                    "dev": 1,
                }
            )
            buffers.append(_array_buffer(host))
        elif isinstance(leaf, np.ndarray):
            host = leaf if leaf.flags["C_CONTIGUOUS"] else np.ascontiguousarray(leaf)
            if host.dtype == object:
                blob = serialization.dumps(host)
                manifest_leaves.append({"k": "pkl", "n": len(blob)})
                buffers.append(blob)
            else:
                manifest_leaves.append(
                    {
                        "k": "nd",
                        "dtype": host.dtype.name,
                        "shape": list(host.shape),
                        "n": host.nbytes,
                        "dev": 0,
                    }
                )
                buffers.append(_array_buffer(host))
        elif isinstance(leaf, (bool, int, float, str)) or leaf is None:
            manifest_leaves.append({"k": "py", "v": leaf, "t": type(leaf).__name__})
        else:
            blob = serialization.dumps(leaf)
            manifest_leaves.append({"k": "pkl", "n": len(blob)})
            buffers.append(blob)

    # The skeleton: the original container structure with leaves replaced
    # by indexed slots, pickled (restricted-loads on the far side).
    skeleton = tree_util.tree_unflatten(
        [_LeafSlot(i) for i in range(len(leaves))], treedef
    )
    skeleton_blob = serialization.dumps(_Skeleton(skeleton))
    manifest = json.dumps(
        {
            "v": WIRE_FORMAT_VERSION,
            "leaves": manifest_leaves,
            "skel": len(skeleton_blob),
        },
        separators=(",", ":"),
    ).encode()
    out: List = [struct.pack(">I", len(manifest)), manifest, skeleton_blob]
    out.extend(buffers)
    return out


def _shards_tile_axis0(spec, shape) -> bool:
    """True when the wire shards split the array only along axis 0, in
    order, covering it exactly — then the payload region IS the array in
    C order and decode can alias it zero-copy (no np.empty + assembly)."""
    if not shape:
        return False
    pos = 0
    for entry in spec["shards"]:
        idx = entry["idx"]
        if idx[0][0] != pos:
            return False
        for (s, e), dim in zip(idx[1:], shape[1:]):
            if s != 0 or e != dim:
                return False
        pos = idx[0][1]
    return pos == shape[0]


def _place_shards_direct(mv, offset, spec, dtype, shape, sharding):
    """device_put each wire shard straight onto its target device.

    When this process's addressable region of the receiver sharding is a
    subset of the sender's shard layout, each local shard goes
    host→device with NO intermediate whole-array assembly (the big win
    on real hardware: per-shard H2D instead of host concat + re-split).
    On a multi-host party mesh each process places only ITS OWN local
    regions out of the full wire payload and the result is assembled
    with ``make_array_from_single_device_arrays`` — which accepts a
    non-fully-addressable (global) sharding.  Returns (array,
    new_offset) or (None, offset) to signal the host-assembly fallback.
    """
    try:
        idx_map = sharding.addressable_devices_indices_map(shape)
    except Exception:
        return None, offset
    by_index: Dict[tuple, list] = {}
    for dev, idx in idx_map.items():
        key = tuple(
            (sl.start or 0, sl.stop if sl.stop is not None else dim)
            for sl, dim in zip(idx, shape)
        )
        by_index.setdefault(key, []).append(dev)
    wire_keys = [
        tuple((s, e) for s, e in entry["idx"]) for entry in spec["shards"]
    ]
    if not set(by_index) <= set(wire_keys):
        return None, offset
    arrays = []
    off = offset
    for entry, key in zip(spec["shards"], wire_keys):
        n = entry["n"]
        if key in by_index:
            extents = [e - s for s, e in entry["idx"]]
            host = np.frombuffer(mv[off : off + n], dtype=dtype).reshape(extents)
            for dev in by_index[key]:  # replicated axes: one copy per device
                arrays.append(jax.device_put(host, dev))
        off += n
    arr = jax.make_array_from_single_device_arrays(shape, sharding, arrays)
    return arr, off


_PY_CASTS = {"bool": bool, "int": int, "float": float, "str": str}


def decode_payload(
    payload: memoryview | bytes,
    allowed: Optional[Dict[str, Any]] = None,
    device_put: bool = False,
    device: Any = None,
    mesh: Any = None,
    zero_copy: bool = False,
) -> Any:
    """Decode wire buffers back into the original pytree.

    ``allowed`` is the serializing allowlist (applied to every pickled
    sub-blob including the skeleton).  With ``device_put=True``, leaves
    that were device arrays on the sender are placed back onto local
    devices (``device``: a Device or Sharding, defaults to JAX default).
    ``mesh``: the receiver's party mesh — shard-encoded leaves whose
    sender sharding fits it are device_put with the equivalent local
    NamedSharding (per-shard placement instead of replication).
    ``zero_copy``: without device_put, large array leaves decode as
    READONLY views aliasing the payload — plain ``nd`` leaves at or
    above :data:`ND_ZERO_COPY_MIN_BYTES`, and shard-streamed leaves
    whose wire layout is already C-order (no assembly copy) — opt-in
    because in-place consumers need writable arrays; small leaves stay
    writable copies so a retained view can't pin a huge payload.
    """
    mv = memoryview(payload)
    (mlen,) = struct.unpack(">I", mv[:4])
    offset = 4
    manifest = json.loads(bytes(mv[offset : offset + mlen]))
    offset += mlen
    fmt_version = manifest.get("v", 1)
    if fmt_version > WIRE_FORMAT_VERSION:
        raise ValueError(
            f"payload uses wire format v{fmt_version}; this receiver "
            f"understands up to v{WIRE_FORMAT_VERSION} — upgrade the "
            f"receiving party"
        )
    skel_len = manifest["skel"]
    skeleton_obj = serialization.loads(bytes(mv[offset : offset + skel_len]), allowed)
    offset += skel_len
    if not isinstance(skeleton_obj, _Skeleton):
        raise ValueError("corrupt payload: missing skeleton")

    leaves: List[Any] = []
    for spec in manifest["leaves"]:
        kind = spec["k"]
        if kind == "nd":
            n = spec["n"]
            as_view = (
                zero_copy
                and n >= ND_ZERO_COPY_MIN_BYTES
                and not (spec.get("dev") and device_put)
            )
            if as_view:
                # Zero-copy opt-in, large leaves only: READONLY view
                # aliasing the payload (same contract as the "nds" path
                # below) — e.g. a packed-tree buffer below the
                # shard-stream threshold decodes with no memcpy at all.
                region = mv[offset : offset + n].toreadonly()
                arr = np.frombuffer(region, dtype=np.dtype(spec["dtype"]))
            else:
                arr = np.frombuffer(
                    mv[offset : offset + n], dtype=np.dtype(spec["dtype"])
                )
            arr = arr.reshape(spec["shape"])
            offset += n
            if spec.get("dev") and device_put:
                # Zero-copy path: device_put copies host→HBM directly from
                # the received buffer; no intermediate host materialization.
                arr = jax.device_put(arr, device) if device is not None else jax.device_put(arr)
            elif not as_view:
                # Host-array leaves must be writable (reference's pickle
                # path returned writable arrays) and must not pin the whole
                # payload buffer alive — one copy, same cost as pickle.
                arr = arr.copy()
            leaves.append(arr)
        elif kind == "nds":
            dtype = np.dtype(spec["dtype"])
            shape = tuple(spec["shape"])
            sharding = None
            if device_put:
                sharding = device if device is not None else resolve_sharding(
                    spec.get("spec"), mesh
                )
            placed = None
            if sharding is not None:
                placed, new_offset = _place_shards_direct(
                    mv, offset, spec, dtype, shape, sharding
                )
            if placed is None and sharding is not None:
                if not getattr(sharding, "is_fully_addressable", True):
                    # Direct placement failed and a whole-array
                    # device_put onto a global (multi-host) sharding
                    # would throw — decode to the default placement and
                    # let the caller re-shard explicitly.
                    sharding = None
            if placed is not None:
                leaves.append(placed)
                offset = new_offset
            elif (device_put or zero_copy) and _shards_tile_axis0(spec, shape):
                # Shards split only axis 0, in wire order: the payload
                # region already IS the array in C order — alias it
                # zero-copy instead of np.empty + per-shard assembly
                # (which costs a full memcpy plus ~30k page faults per
                # 128 MB at wire rates).  With device_put the view only
                # feeds the H2D copy; without (zero_copy opt-in) the
                # caller gets a READONLY view pinning the payload buffer
                # — the array is ~the whole payload, so nothing wasted.
                total = sum(e["n"] for e in spec["shards"])
                # toreadonly(): the live receive path hands us a
                # bytearray, whose views are writable — the zero-copy
                # contract is a READONLY alias (mutating it would
                # corrupt the shared wire buffer silently).
                region = mv[offset : offset + total].toreadonly()
                out = np.frombuffer(region, dtype=dtype).reshape(shape)
                offset += total
                if device_put:
                    out = (
                        jax.device_put(out, sharding)
                        if sharding is not None
                        else jax.device_put(out)
                    )
                leaves.append(out)
            else:
                out = np.empty(shape, dtype)
                for entry in spec["shards"]:
                    idx = tuple(slice(s, e) for s, e in entry["idx"])
                    extents = [e - s for s, e in entry["idx"]]
                    n = entry["n"]
                    out[idx] = np.frombuffer(
                        mv[offset : offset + n], dtype=dtype
                    ).reshape(extents)
                    offset += n
                if device_put:
                    arr = (
                        jax.device_put(out, sharding)
                        if sharding is not None
                        else jax.device_put(out)
                    )
                    leaves.append(arr)
                else:
                    leaves.append(out)
        elif kind == "pkl":
            n = spec["n"]
            leaves.append(serialization.loads(bytes(mv[offset : offset + n]), allowed))
            offset += n
        elif kind == "py":
            v = spec["v"]
            cast = _PY_CASTS.get(spec.get("t", ""))
            leaves.append(cast(v) if (cast is not None and v is not None) else v)
        else:  # pragma: no cover
            raise ValueError(f"unknown leaf kind {kind!r}")

    slots, treedef = tree_util.tree_flatten(
        skeleton_obj.tree, is_leaf=lambda x: isinstance(x, _LeafSlot)
    )
    ordered = [leaves[s.index] for s in slots]
    return tree_util.tree_unflatten(ordered, treedef)


def payload_nbytes(buffers: List) -> int:
    return sum(len(b) if isinstance(b, (bytes, bytearray)) else b.nbytes for b in buffers)


# ---------------------------------------------------------------------------
# Stream/delta frames (wire format v3)
# ---------------------------------------------------------------------------
#
# A DATA frame sent on a named *stream* carries extra header fields:
#
#   stm   stream key (stable across rounds; scopes the delta cache)
#   ccsz  chunk size the per-chunk CRCs / bitmap refer to
#   ccrc  list of per-chunk CRC32 (zlib) values, one per TRANSMITTED
#         chunk in payload order — the receiver verifies each chunk and
#         skips the whole-payload CRC re-check entirely
#   dlt   delta manifest (absent on a full send):
#           total  full logical payload length in bytes
#           map    hex bitmap, bit i set = chunk i of the logical
#                  payload is INCLUDED in this frame (it changed)
#           bfp    fingerprint of the base payload the delta applies to
#                  (crc32 over the base's packed per-chunk CRC words) —
#                  a mismatch means the receiver's cached base desynced
#                  (e.g. peer restart) and it replies
#                  code="delta_base" so the sender falls back to a
#                  full payload
#
# CRCs here are zlib.crc32 (always C-speed, stdlib) rather than the
# native CRC32-C path: delta caching must not degrade to a ~MB/s pure-
# Python checksum when the native codec isn't built.


def chunk_crcs(buf, chunk_bytes: int = DELTA_CHUNK_BYTES) -> List[int]:
    """Per-chunk zlib CRC32 of ``buf`` (last chunk may be short)."""
    import zlib

    mv = memoryview(buf)
    if mv.format != "B":
        mv = mv.cast("B")
    return [
        zlib.crc32(mv[off : off + chunk_bytes])
        for off in range(0, len(mv), chunk_bytes)
    ] or [zlib.crc32(b"")]


def crc_fingerprint(crcs: List[int]) -> int:
    """One fingerprint of a payload from its per-chunk CRC list.

    Cheap to maintain incrementally (patch the changed chunks' words and
    re-hash the small list) — both ends use it to prove their delta
    bases match without re-hashing the multi-GB payload."""
    import zlib

    return zlib.crc32(b"".join(struct.pack(">I", c) for c in crcs))


def encode_chunk_bitmap(indices: List[int], nchunks: int) -> str:
    """Hex bitmap with bit ``i`` set for every included chunk index."""
    bits = bytearray((nchunks + 7) // 8)
    for i in indices:
        bits[i >> 3] |= 1 << (i & 7)
    return bits.hex()


def decode_chunk_bitmap(hexmap: str, nchunks: int) -> List[int]:
    bits = bytes.fromhex(hexmap)
    return [i for i in range(nchunks) if bits[i >> 3] & (1 << (i & 7))]


def make_delta_manifest(
    total: int, bitmap_hex: str, base_fp: Optional[int] = None
) -> Dict[str, Any]:
    """The ``dlt`` header field — the single producer of its schema
    (``tool/check_wire_format.py`` fingerprints it).

    ``base_fp=None`` (v4 stripe frames only) omits ``bfp``: the frame's
    chunks are a segment of a FRESH payload to assemble, not a delta
    against a cached base.  Ordinary delta frames always carry ``bfp``.
    """
    d: Dict[str, Any] = {"total": int(total), "map": bitmap_hex}
    if base_fp is not None:
        d["bfp"] = int(base_fp)
    return d


def make_stripe_marker(sid: int, nf: int) -> Dict[str, int]:
    """The ``stp`` header field of a multi-rail stripe frame (wire v4).

    ``sid`` — payload generation id, monotonically increasing per
    client: a retry re-ships the whole payload under a fresh sid and
    the receiver discards any stale partial assembly for the same
    rendezvous.  ``nf`` — total frames in this payload's stripe group;
    assembly completes when all ``nf`` frames verified.  Single
    producer of the schema (fingerprinted by tool/check_wire_format).
    """
    return {"sid": int(sid), "nf": int(nf)}
