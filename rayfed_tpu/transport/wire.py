"""Wire format: frames + zero-copy tensor payload codec.

The reference ships ``cloudpickle.dumps(data)`` of whole Python objects
(``barriers.py:151``) — for device arrays that means device→host copy,
pickle memcpy, and a pickle parse on the far side.  Here array leaves
travel as **raw buffers** described by a small JSON manifest: the receiver
reconstructs ndarrays with ``np.frombuffer`` (zero-copy) and can
``jax.device_put`` them directly, optionally with a target sharding.
Non-array leaves fall back to (allowlist-restricted) pickle per skeleton.

Frame layout (all integers big-endian)::

    magic   4s   b"RFW1"
    type    u8   DATA=1 ACK=2 PING=3 PONG=4 ERR=5
    flags   u8
    hlen    u32  header (JSON) length
    plen    u64  payload length
    header  hlen bytes of JSON
    payload plen bytes

Header fields: ``rid`` (request id for ACK matching), ``src`` party,
``up``/``down`` rendezvous seq ids, ``meta`` metadata headers.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

try:  # registers 'bfloat16' & friends as numpy dtypes (jax dependency)
    import ml_dtypes  # noqa: F401
except ImportError:  # pragma: no cover
    pass

from rayfed_tpu import serialization
from rayfed_tpu import tree_util

MAGIC = b"RFW1"
_HEADER_STRUCT = struct.Struct(">4sBBIQ")
HEADER_SIZE = _HEADER_STRUCT.size

MSG_DATA = 1
MSG_ACK = 2
MSG_PING = 3
MSG_PONG = 4
MSG_ERR = 5


def pack_frame(
    msg_type: int,
    header: Dict[str, Any],
    payload: bytes = b"",
    payload_len: Optional[int] = None,
) -> List:
    """Returns a list of buffers to write (avoids concatenating the payload).

    ``payload_len`` lets a caller declare the length of payload buffers it
    will write itself (vectored sends) — this is the single producer of
    frame prefixes for both client and server.
    """
    hdr = json.dumps(header, separators=(",", ":")).encode()
    plen = payload_len if payload_len is not None else len(payload)
    prefix = _HEADER_STRUCT.pack(MAGIC, msg_type, 0, len(hdr), plen)
    out = [prefix, hdr]
    if payload:
        out.append(payload)
    return out


def unpack_frame_prefix(prefix: bytes) -> Tuple[int, int, int, int]:
    magic, msg_type, flags, hlen, plen = _HEADER_STRUCT.unpack(prefix)
    if magic != MAGIC:
        raise ValueError(f"bad frame magic {magic!r}")
    return msg_type, flags, hlen, plen


# ---------------------------------------------------------------------------
# Tensor payload codec
# ---------------------------------------------------------------------------


class _LeafSlot:
    """Placeholder for a leaf inside the pickled container skeleton."""

    __slots__ = ("index",)

    def __init__(self, index: int) -> None:
        self.index = index

    def __reduce__(self):
        return (_LeafSlot, (self.index,))


class _Skeleton:
    """Wrapper marking the pickled skeleton object."""

    __slots__ = ("tree",)

    def __init__(self, tree: Any) -> None:
        self.tree = tree

    def __reduce__(self):
        return (_Skeleton, (self.tree,))


def _is_array_leaf(x: Any) -> bool:
    return isinstance(x, (np.ndarray, jax.Array))


def _array_buffer(host: np.ndarray) -> memoryview:
    """Zero-copy byte view; handles dtypes outside the buffer protocol (bf16, fp8)."""
    try:
        return memoryview(host).cast("B")
    except (ValueError, TypeError):
        return memoryview(host.reshape(-1).view(np.uint8))


def encode_payload(obj: Any) -> List:
    """Encode a pytree into wire buffers: ``[u32 manifest_len, manifest, *bufs]``.

    Array leaves (``jax.Array`` / ``np.ndarray``) become raw buffers; jax
    arrays are fetched to host once (``device_get``).  Everything else —
    including the container skeleton — is pickled.  Returns a list of
    buffers suitable for vectored writes (no large concatenation).
    """
    leaves, treedef = tree_util.tree_flatten(obj)
    manifest_leaves: List[Dict[str, Any]] = []
    buffers: List = []
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            host = np.asarray(jax.device_get(leaf))
            if not host.flags["C_CONTIGUOUS"]:
                # NB: np.ascontiguousarray promotes 0-d to (1,) — only
                # call it when actually needed (0-d is always contiguous).
                host = np.ascontiguousarray(host)
            manifest_leaves.append(
                {
                    "k": "nd",
                    "dtype": host.dtype.name,
                    "shape": list(host.shape),
                    "n": host.nbytes,
                    "dev": 1,
                }
            )
            buffers.append(_array_buffer(host))
        elif isinstance(leaf, np.ndarray):
            host = leaf if leaf.flags["C_CONTIGUOUS"] else np.ascontiguousarray(leaf)
            if host.dtype == object:
                blob = serialization.dumps(host)
                manifest_leaves.append({"k": "pkl", "n": len(blob)})
                buffers.append(blob)
            else:
                manifest_leaves.append(
                    {
                        "k": "nd",
                        "dtype": host.dtype.name,
                        "shape": list(host.shape),
                        "n": host.nbytes,
                        "dev": 0,
                    }
                )
                buffers.append(_array_buffer(host))
        elif isinstance(leaf, (bool, int, float, str)) or leaf is None:
            manifest_leaves.append({"k": "py", "v": leaf, "t": type(leaf).__name__})
        else:
            blob = serialization.dumps(leaf)
            manifest_leaves.append({"k": "pkl", "n": len(blob)})
            buffers.append(blob)

    # The skeleton: the original container structure with leaves replaced
    # by indexed slots, pickled (restricted-loads on the far side).
    skeleton = tree_util.tree_unflatten(
        [_LeafSlot(i) for i in range(len(leaves))], treedef
    )
    skeleton_blob = serialization.dumps(_Skeleton(skeleton))
    manifest = json.dumps(
        {"leaves": manifest_leaves, "skel": len(skeleton_blob)},
        separators=(",", ":"),
    ).encode()
    out: List = [struct.pack(">I", len(manifest)), manifest, skeleton_blob]
    out.extend(buffers)
    return out


_PY_CASTS = {"bool": bool, "int": int, "float": float, "str": str}


def decode_payload(
    payload: memoryview | bytes,
    allowed: Optional[Dict[str, Any]] = None,
    device_put: bool = False,
    device: Any = None,
) -> Any:
    """Decode wire buffers back into the original pytree.

    ``allowed`` is the serializing allowlist (applied to every pickled
    sub-blob including the skeleton).  With ``device_put=True``, leaves
    that were device arrays on the sender are placed back onto local
    devices (``device``: a Device or Sharding, defaults to JAX default).
    """
    mv = memoryview(payload)
    (mlen,) = struct.unpack(">I", mv[:4])
    offset = 4
    manifest = json.loads(bytes(mv[offset : offset + mlen]))
    offset += mlen
    skel_len = manifest["skel"]
    skeleton_obj = serialization.loads(bytes(mv[offset : offset + skel_len]), allowed)
    offset += skel_len
    if not isinstance(skeleton_obj, _Skeleton):
        raise ValueError("corrupt payload: missing skeleton")

    leaves: List[Any] = []
    for spec in manifest["leaves"]:
        kind = spec["k"]
        if kind == "nd":
            n = spec["n"]
            arr = np.frombuffer(mv[offset : offset + n], dtype=np.dtype(spec["dtype"]))
            arr = arr.reshape(spec["shape"])
            offset += n
            if spec.get("dev") and device_put:
                # Zero-copy path: device_put copies host→HBM directly from
                # the received buffer; no intermediate host materialization.
                arr = jax.device_put(arr, device) if device is not None else jax.device_put(arr)
            else:
                # Host-array leaves must be writable (reference's pickle
                # path returned writable arrays) and must not pin the whole
                # payload buffer alive — one copy, same cost as pickle.
                arr = arr.copy()
            leaves.append(arr)
        elif kind == "pkl":
            n = spec["n"]
            leaves.append(serialization.loads(bytes(mv[offset : offset + n]), allowed))
            offset += n
        elif kind == "py":
            v = spec["v"]
            cast = _PY_CASTS.get(spec.get("t", ""))
            leaves.append(cast(v) if (cast is not None and v is not None) else v)
        else:  # pragma: no cover
            raise ValueError(f"unknown leaf kind {kind!r}")

    slots, treedef = tree_util.tree_flatten(
        skeleton_obj.tree, is_leaf=lambda x: isinstance(x, _LeafSlot)
    )
    ordered = [leaves[s.index] for s in slots]
    return tree_util.tree_unflatten(ordered, treedef)


def payload_nbytes(buffers: List) -> int:
    return sum(len(b) if isinstance(b, (bytes, bytearray)) else b.nbytes for b in buffers)
