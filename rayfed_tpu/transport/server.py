"""Asyncio transport server — the receive side of the push transport.

Plays the role of the reference's ``RecverProxyActor`` gRPC server
(``barriers.py:93-118, 280-351``) without an actor framework: one
listener per party, frames demuxed into the rendezvous :class:`Mailbox`.

Implementation is an ``asyncio.BufferedProtocol`` frame parser rather
than the (simpler) StreamReader: payload bytes land **directly** in a
preallocated per-frame ``bytearray`` via ``get_buffer``/``buffer_updated``
— no 64 KiB chunk joins, no intermediate copies.  On localhost this is
~3.5× the StreamReader read path; the decode side then reads arrays
zero-copy out of the same buffer (``np.frombuffer`` → ``device_put``).
TLS (including mutual auth) is plain ``ssl`` on the listener (asyncio's
sslproto supports buffered protocols on 3.11+).

Per-connection frame order is preserved: checksum verification of large
payloads runs off-loop while the socket is paused, so other connections
keep flowing.
"""

from __future__ import annotations

import asyncio
import json
import logging
import ssl
import time
from typing import Any, Callable, Dict, Optional

from rayfed_tpu.transport import wire
from rayfed_tpu.transport.rendezvous import Mailbox, Message

logger = logging.getLogger(__name__)

_PREFIX_SIZE = wire.HEADER_SIZE
# Payloads at or above this size get their checksum verified off-loop.
_OFFLOAD_CRC_BYTES = 4 * 1024 * 1024
# Payloads at or above this size are read off-loop: the protocol pauses
# and a blocking recv_into loop in an executor thread drains the socket
# straight into the preallocated payload buffer — no per-chunk event-loop
# callbacks for the bulk bytes (mirrors the client's writev send path).
_RAW_READ_BYTES = 4 * 1024 * 1024
# Headers are small JSON (ids + metadata); a corrupt or hostile peer must
# not be able to force a multi-GB allocation via the 32-bit hlen field.
_MAX_HEADER_BYTES = 1 * 1024 * 1024


class _FrameProtocol(asyncio.BufferedProtocol):
    """One connection's incremental frame parser (prefix → header → payload)."""

    def __init__(self, server: "TransportServer") -> None:
        self._server = server
        self._transport: Optional[asyncio.Transport] = None
        # Parse state
        self._small = bytearray(_PREFIX_SIZE)
        self._small_view = memoryview(self._small)
        self._need = _PREFIX_SIZE
        self._got = 0
        self._state = "prefix"  # prefix | header | payload | trailer
        self._msg_type = 0
        self._flags = 0
        self._hlen = 0
        self._plen = 0
        self._header: Dict[str, Any] = {}
        self._payload: Optional[bytearray] = None
        self._payload_view: Optional[memoryview] = None
        self._payload_t0 = 0.0
        self._trailer_crc: Optional[int] = None
        self._peer = None
        self._closed = False

    # -- protocol callbacks ---------------------------------------------------

    def connection_made(self, transport) -> None:
        self._transport = transport
        self._peer = transport.get_extra_info("peername")

    def connection_lost(self, exc) -> None:
        self._closed = True

    def get_buffer(self, sizehint: int) -> memoryview:
        if self._state == "payload":
            if self._payload_t0 == 0.0:
                self._payload_t0 = time.perf_counter()
            return self._payload_view[self._got :]
        return self._small_view[self._got : self._need]

    def buffer_updated(self, nbytes: int) -> None:
        if self._state == "payload" and nbytes:
            # Mid-payload liveness: the health monitor counts bytes
            # actively arriving from a party as proof of life, so a
            # multi-GB push can't get its sender declared dead just
            # because control pings queue behind the bulk transfer.
            self._server.note_rx_progress(self._header.get("src"), nbytes)
        self._got += nbytes
        if self._got < self._need:
            return
        try:
            if self._state == "prefix":
                self._on_prefix()
            elif self._state == "header":
                self._on_header()
            elif self._state == "trailer":
                self._on_trailer()
            else:
                self._on_payload()
        except Exception:
            logger.exception(
                "[%s] frame parse error (peer=%s)", self._server._party, self._peer
            )
            self._abort()

    # -- state transitions ----------------------------------------------------

    def _expect(self, state: str, need: int) -> None:
        self._state = state
        self._need = need
        self._got = 0
        if state != "payload" and need > len(self._small):
            self._small = bytearray(need)
            self._small_view = memoryview(self._small)

    def _on_prefix(self) -> None:
        msg_type, flags, hlen, plen = wire.unpack_frame_prefix(
            bytes(self._small_view[:_PREFIX_SIZE])
        )
        self._msg_type = msg_type
        self._flags = flags
        self._hlen = hlen
        self._plen = plen
        if hlen > _MAX_HEADER_BYTES:
            # Can't even read a header this size to echo a request id —
            # drop the connection before allocating anything.
            logger.warning(
                "[%s] header of %d bytes exceeds cap %d (peer=%s); closing",
                self._server._party, hlen, _MAX_HEADER_BYTES, self._peer,
            )
            self._abort()
            return
        if plen > self._server._max_message_size:
            # Fatal (non-retryable).  Read the header (to echo rid), reply,
            # then close — never allocate the oversized payload.
            self._expect("header", hlen) if hlen else self._fatal_oversize({})
            self._oversize = True
            return
        self._oversize = False
        if hlen:
            self._expect("header", hlen)
        else:
            self._header = {}
            self._begin_payload()

    def _on_header(self) -> None:
        self._header = json.loads(bytes(self._small_view[: self._hlen]))
        if getattr(self, "_oversize", False):
            self._fatal_oversize(self._header)
            return
        self._begin_payload()

    def _begin_payload(self) -> None:
        if self._plen == 0:
            self._payload = bytearray(0)
            if self._flags & wire.FLAG_CRC_TRAILER:
                self._expect("trailer", 4)
            else:
                self._dispatch_frame()
            return
        self._payload = bytearray(self._plen)
        self._payload_view = memoryview(self._payload)
        self._payload_t0 = 0.0
        if self._plen >= _RAW_READ_BYTES:
            sock = (
                None
                if self._server._ssl_context is not None
                else self._transport.get_extra_info("socket")
            )
            if sock is not None:
                # Off-loop bulk read.  Safe w.r.t. buffering: get_buffer
                # windows are exact, so at this point the transport holds
                # no payload bytes — they're all still in the kernel.
                self._transport.pause_reading()
                self._payload_t0 = time.perf_counter()
                loop = asyncio.get_running_loop()
                fut = loop.run_in_executor(None, self._raw_read, sock.fileno())
                fut.add_done_callback(
                    lambda f: loop.call_soon_threadsafe(self._raw_read_done, f)
                )
                return
        self._expect("payload", self._plen)

    def _raw_read(self, fd: int) -> None:
        """Drain the payload into the preallocated buffer via os.readv on
        the raw fd (executor thread; the socket stays non-blocking —
        EAGAIN polls for readability).

        ``select.poll`` (not select) — no FD_SETSIZE limit — and an IDLE
        deadline (reset on every successful read) so a peer that
        declares a payload then stalls cannot pin a shared executor
        thread forever, while a slow-but-flowing large transfer is never
        cut off.
        """
        import os
        import select

        idle_limit = 120.0
        deadline = time.monotonic() + idle_limit
        poller = select.poll()
        poller.register(fd, select.POLLIN)
        view = self._payload_view
        src = self._header.get("src")
        got = 0
        while got < len(view):
            try:
                r = os.readv(fd, [view[got:]])
                if r == 0:
                    raise ConnectionError("peer closed mid-payload")
                got += r
                # Same liveness signal as the protocol path (note_rx_
                # progress tolerates this executor-thread caller).
                self._server.note_rx_progress(src, r)
                deadline = time.monotonic() + idle_limit
            except (BlockingIOError, InterruptedError):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ConnectionError(
                        f"peer stalled mid-payload ({got}/{len(view)} bytes)"
                    )
                poller.poll(min(remaining, 10.0) * 1000)

    def _raw_read_done(self, fut) -> None:
        try:
            fut.result()
        except Exception as e:
            if not self._closed:
                logger.warning(
                    "[%s] payload read failed (peer=%s): %s",
                    self._server._party, self._peer, e,
                )
                self._abort()
            return
        if self._closed:
            return
        self._transport.resume_reading()
        self._got = self._need = self._plen  # state as if read via protocol
        self._state = "payload"
        if self._flags & wire.FLAG_CRC_TRAILER:
            self._expect("trailer", 4)
        else:
            self._dispatch_frame()

    def _on_payload(self) -> None:
        if self._flags & wire.FLAG_CRC_TRAILER:
            self._expect("trailer", 4)
            return
        self._dispatch_frame()

    def _on_trailer(self) -> None:
        import struct

        (self._trailer_crc,) = struct.unpack(">I", bytes(self._small_view[:4]))
        self._dispatch_frame()

    def _reset(self) -> None:
        self._payload = None
        self._payload_view = None
        self._expect("prefix", _PREFIX_SIZE)

    # -- frame handling -------------------------------------------------------

    def _reply(self, msg_type: int, header: Dict[str, Any]) -> None:
        if self._transport is None or self._closed:
            return
        for buf in wire.pack_frame(msg_type, header):
            self._transport.write(buf)

    def _abort(self) -> None:
        if self._transport is not None:
            self._transport.close()
        self._closed = True

    def _fatal_oversize(self, header: Dict[str, Any]) -> None:
        self._reply(
            wire.MSG_ERR,
            {
                "rid": header.get("rid"),
                "fatal": True,
                "error": f"message of {self._plen} bytes exceeds max "
                f"{self._server._max_message_size}",
            },
        )
        # Close: the oversized payload is still in flight on the socket and
        # we refuse to buffer it.
        if self._transport is not None:
            # Give the reply a chance to flush before close.
            asyncio.get_running_loop().call_soon(self._abort)
        self._state = "drop"
        self._need = 1 << 62  # swallow whatever arrives until close

    def _dispatch_frame(self) -> None:
        server = self._server
        msg_type = self._msg_type
        header = self._header
        payload = self._payload if self._payload is not None else bytearray(0)
        read_seconds = (
            (time.perf_counter() - self._payload_t0) if self._payload_t0 else 0.0
        )
        trailer_crc = self._trailer_crc
        self._trailer_crc = None
        if trailer_crc is not None and "crc" not in header:
            header = dict(header, crc=trailer_crc)
        self._reset()

        if msg_type == wire.MSG_PING:
            self._reply(wire.MSG_PONG, {"rid": header.get("rid")})
            return
        if msg_type != wire.MSG_DATA:
            logger.warning(
                "[%s] unexpected frame type %s from %s",
                server._party, msg_type, self._peer,
            )
            self._abort()
            return

        expected_crc = header.get("crc")
        if expected_crc is not None:
            from rayfed_tpu import native

            if not native.is_available():
                # Advisory checksum: without the fast C++ path, verifying
                # at python speed would stall the pipeline — trust TCP.
                if not server._warned_no_native_crc:
                    server._warned_no_native_crc = True
                    logger.warning(
                        "[%s] peer sends checksums but native codec is "
                        "unavailable; skipping verification", server._party,
                    )
                expected_crc = None

        if expected_crc is not None and len(payload) >= _OFFLOAD_CRC_BYTES:
            # Big frame: verify off-loop; pause reading so per-connection
            # order holds without buffering unbounded frames.
            transport = self._transport
            if transport is not None:
                transport.pause_reading()
            loop = asyncio.get_running_loop()
            fut = loop.run_in_executor(None, _crc_of, payload)

            def _done(f):
                try:
                    actual = f.result()
                except Exception as e:  # pragma: no cover
                    logger.exception("[%s] crc executor error: %s", server._party, e)
                    self._abort()
                    return
                finally:
                    if transport is not None and not self._closed:
                        transport.resume_reading()
                self._finish_data(header, payload, read_seconds, expected_crc, actual)

            fut.add_done_callback(
                lambda f: loop.call_soon_threadsafe(_done, f)
            )
            return

        actual = None
        if expected_crc is not None:
            actual = _crc_of(payload)
        self._finish_data(header, payload, read_seconds, expected_crc, actual)

    def _finish_data(
        self, header, payload, read_seconds, expected_crc, actual
    ) -> None:
        server = self._server
        if expected_crc is not None and actual != expected_crc:
            server.stats["receive_crc_errors"] = (
                server.stats.get("receive_crc_errors", 0) + 1
            )
            self._reply(
                wire.MSG_ERR,
                {
                    "rid": header.get("rid"),
                    "error": f"payload checksum mismatch "
                    f"({actual:#x} != {expected_crc:#x})",
                },
            )
            return
        message = Message(
            src_party=header.get("src", "?"),
            upstream_seq_id=str(header.get("up")),
            downstream_seq_id=str(header.get("down")),
            payload=payload,
            metadata=header.get("meta", {}),
            read_seconds=read_seconds,
            error=header.get("err"),
        )
        server.stats["receive_op_count"] += 1
        server.stats["receive_bytes"] += len(payload)
        if server._on_message is not None:
            server._on_message(message)
        server._mailbox.put(message)
        self._reply(wire.MSG_ACK, {"rid": header.get("rid"), "result": "OK"})


def _crc_of(payload) -> int:
    from rayfed_tpu import native

    return native.crc32c(payload)


class TransportServer:
    def __init__(
        self,
        party: str,
        listen_addr: str,
        mailbox: Mailbox,
        max_message_size: int,
        ssl_context: Optional[ssl.SSLContext] = None,
        on_message: Optional[Callable[[Message], None]] = None,
    ) -> None:
        self._party = party
        host, _, port = listen_addr.rpartition(":")
        self._host = host or "0.0.0.0"
        self._port = int(port)
        self._mailbox = mailbox
        self._max_message_size = max_message_size
        self._ssl_context = ssl_context
        self._server: Optional[asyncio.AbstractServer] = None
        self._on_message = on_message
        self._warned_no_native_crc = False
        self.stats: Dict[str, Any] = {"receive_op_count": 0, "receive_bytes": 0}
        # Per-party monotonically growing byte counters INCLUDING bytes
        # of payloads still in flight (the completed-put counters above
        # only move at frame boundaries).  Written from the loop thread
        # and the raw-read executor threads: plain dict ops are atomic
        # under the GIL, and a (rare) lost += only delays the health
        # monitor's liveness credit by one ping cycle.
        self._rx_progress: Dict[str, int] = {}

    def note_rx_progress(self, party: Optional[str], nbytes: int) -> None:
        if party:
            self._rx_progress[party] = self._rx_progress.get(party, 0) + nbytes

    def receive_progress(self) -> Dict[str, int]:
        """Snapshot of per-party received bytes (incl. in-flight payloads)."""
        return dict(self._rx_progress)

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._server = await loop.create_server(
            lambda: _FrameProtocol(self),
            host=self._host,
            port=self._port,
            ssl=self._ssl_context,
        )
        if self._port == 0:  # OS-assigned (bridge listeners)
            self._port = self._server.sockets[0].getsockname()[1]
        logger.debug("[%s] transport server listening on %s:%s",
                     self._party, self._host, self._port)

    @property
    def bound_port(self) -> int:
        return self._port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
