"""Asyncio transport server — the receive side of the push transport.

Plays the role of the reference's ``RecverProxyActor`` gRPC server
(``barriers.py:93-118, 280-351``) without an actor framework: one
listener per party, frames demuxed into the rendezvous :class:`Mailbox`.

Implementation is an ``asyncio.BufferedProtocol`` frame parser rather
than the (simpler) StreamReader: payload bytes land **directly** in a
preallocated per-frame ``bytearray`` via ``get_buffer``/``buffer_updated``
— no 64 KiB chunk joins, no intermediate copies.  On localhost this is
~3.5× the StreamReader read path; the decode side then reads arrays
zero-copy out of the same buffer (``np.frombuffer`` → ``device_put``).
TLS (including mutual auth) is plain ``ssl`` on the listener (asyncio's
sslproto supports buffered protocols on 3.11+).

Per-connection frame order is preserved: checksum verification of large
payloads runs off-loop while the socket is paused, so other connections
keep flowing.
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import ssl
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from rayfed_tpu import chaos
from rayfed_tpu import telemetry
from rayfed_tpu.transport import local
from rayfed_tpu.transport import wire
from rayfed_tpu.transport.rendezvous import Mailbox, Message

logger = logging.getLogger(__name__)

_PREFIX_SIZE = wire.HEADER_SIZE
# Payloads at or above this size get their checksum verified off-loop.
_OFFLOAD_CRC_BYTES = 4 * 1024 * 1024
# Payloads at or above this size are read off-loop: the protocol pauses
# and a blocking recv_into loop in an executor thread drains the socket
# straight into the preallocated payload buffer — no per-chunk event-loop
# callbacks for the bulk bytes (mirrors the client's writev send path).
_RAW_READ_BYTES = 4 * 1024 * 1024
# Headers are small JSON (ids + metadata); a corrupt or hostile peer must
# not be able to force a multi-GB allocation via the 32-bit hlen field.
_MAX_HEADER_BYTES = 1 * 1024 * 1024
# Delta bases retained per server: one full payload per (src, stream) —
# bounded LRU so a peer cycling stream names can't grow memory unbounded.
_MAX_DELTA_BASES = 32
# In-progress multi-rail stripe reassemblies retained (wire v4): one
# payload-sized buffer each, keyed by rendezvous — bounded LRU plus an
# idle-drop so an abandoned sender can't pin payload buffers forever.
_MAX_STRIPE_ASM = 8
_STRIPE_IDLE_DROP_S = 600.0


class _DeltaBaseMissing(Exception):
    """The delta's base payload isn't cached here (restart/desync)."""


class _StripeFatal(Exception):
    """A striped payload rejected for a non-transient reason (e.g. it
    exceeds this server's message-size cap): replied ``fatal`` so the
    sender aborts instead of fruitlessly re-shipping gigabytes — parity
    with the single-frame path's ``_fatal_oversize``."""


class _StripeReject(ValueError):
    """A stripe frame rejected for protocol-STATE reasons — stale sid,
    evicted assembly, geometry disagreement — not data corruption.
    Counted as ``receive_stripe_rejects`` so an eviction burst doesn't
    read as phantom CRC errors in the stats."""


class _StripeAsm:
    """One in-progress multi-rail payload reassembly (wire v4).

    Frames of the same payload land concurrently on different rail
    connections; chunk placement is serialized by the per-assembly
    lock, the map itself by the server's stripe lock.  ``prefix``
    tracks the contiguous VERIFIED chunk prefix — the only bytes a
    chunk sink ever sees, which is what lets a streaming aggregator
    keep folding under shuffled cross-rail arrival.
    """

    __slots__ = (
        "sid", "total", "csz", "nch", "nf", "buf", "ccrc", "have",
        "frames", "is_delta", "prefix", "shipped", "read_s", "lock",
        "touched",
    )

    def __init__(self, sid, total, csz, nch, nf, buf, ccrc, is_delta):
        self.sid = sid
        self.total = total
        self.csz = csz
        self.nch = nch
        self.nf = nf
        self.buf = buf
        self.ccrc = ccrc
        self.have: set = set()
        self.frames = 0
        self.is_delta = is_delta
        self.prefix = 0   # contiguous verified chunks from index 0
        self.shipped = 0  # wire bytes received for this assembly
        self.read_s = 0.0
        self.lock = threading.Lock()
        self.touched = time.monotonic()


class _FrameProtocol(asyncio.BufferedProtocol):
    """One connection's incremental frame parser (prefix → header → payload)."""

    def __init__(self, server: "TransportServer") -> None:
        self._server = server
        self._transport: Optional[asyncio.Transport] = None
        # Parse state
        self._small = bytearray(_PREFIX_SIZE)
        self._small_view = memoryview(self._small)
        self._need = _PREFIX_SIZE
        self._got = 0
        self._state = "prefix"  # prefix | header | payload | trailer
        self._msg_type = 0
        self._flags = 0
        self._hlen = 0
        self._plen = 0
        self._header: Dict[str, Any] = {}
        self._payload: Optional[bytearray] = None
        self._payload_view: Optional[memoryview] = None
        self._payload_t0 = 0.0
        self._trailer_crc: Optional[int] = None
        self._peer = None
        self._closed = False
        # Chunk-granular receive hook: when a sink is registered for this
        # frame's (up, down) key, arriving payload bytes are surfaced to
        # it incrementally (streaming aggregation consumes them while
        # later chunks are still on the wire).  Delta frames skip the
        # incremental feed — their payload is compacted changed chunks,
        # only meaningful after reconstruction.
        self._cur_sink = None

    # -- protocol callbacks ---------------------------------------------------

    def connection_made(self, transport) -> None:
        self._transport = transport
        self._peer = transport.get_extra_info("peername")
        self._server._protocols.add(self)

    def connection_lost(self, exc) -> None:
        self._closed = True
        self._server._protocols.discard(self)
        # A sink that was being fed an in-flight payload must hear that
        # the frame died (the sender will retry on a fresh connection
        # with a fresh buffer) — otherwise it would keep folding from a
        # half-filled stale buffer.
        if self._cur_sink is not None and self._state == "payload":
            try:
                self._cur_sink.on_frame_abort(corrupt=False)
            except Exception:  # pragma: no cover - sink bug
                logger.exception(
                    "[%s] chunk sink abort failed", self._server._party
                )
            self._cur_sink = None

    def get_buffer(self, sizehint: int) -> memoryview:
        if self._state == "payload":
            if self._payload_t0 == 0.0:
                self._payload_t0 = time.perf_counter()
            return self._payload_view[self._got :]
        return self._small_view[self._got : self._need]

    def buffer_updated(self, nbytes: int) -> None:
        if self._state == "payload" and nbytes:
            # Mid-payload liveness: the health monitor counts bytes
            # actively arriving from a party as proof of life, so a
            # multi-GB push can't get its sender declared dead just
            # because control pings queue behind the bulk transfer.
            self._server.note_rx_progress(self._header.get("src"), nbytes)
            if self._cur_sink is not None:
                try:
                    self._cur_sink.on_bytes(
                        self._payload_view, self._got + nbytes
                    )
                except Exception:
                    logger.exception(
                        "[%s] chunk sink failed (peer=%s)",
                        self._server._party, self._peer,
                    )
                    self._cur_sink = None
        self._got += nbytes
        if self._got < self._need:
            return
        try:
            if self._state == "prefix":
                self._on_prefix()
            elif self._state == "header":
                self._on_header()
            elif self._state == "trailer":
                self._on_trailer()
            else:
                self._on_payload()
        except Exception:
            logger.exception(
                "[%s] frame parse error (peer=%s)", self._server._party, self._peer
            )
            self._abort()

    # -- state transitions ----------------------------------------------------

    def _expect(self, state: str, need: int) -> None:
        self._state = state
        self._need = need
        self._got = 0
        if state != "payload" and need > len(self._small):
            self._small = bytearray(need)
            self._small_view = memoryview(self._small)

    def _on_prefix(self) -> None:
        msg_type, flags, hlen, plen = wire.unpack_frame_prefix(
            bytes(self._small_view[:_PREFIX_SIZE])
        )
        self._msg_type = msg_type
        self._flags = flags
        self._hlen = hlen
        self._plen = plen
        if hlen > _MAX_HEADER_BYTES:
            # Can't even read a header this size to echo a request id —
            # drop the connection before allocating anything.
            logger.warning(
                "[%s] header of %d bytes exceeds cap %d (peer=%s); closing",
                self._server._party, hlen, _MAX_HEADER_BYTES, self._peer,
            )
            self._abort()
            return
        if plen > self._server._max_message_size:
            # Fatal (non-retryable).  Read the header (to echo rid), reply,
            # then close — never allocate the oversized payload.
            self._expect("header", hlen) if hlen else self._fatal_oversize({})
            self._oversize = True
            return
        self._oversize = False
        if hlen:
            self._expect("header", hlen)
        else:
            self._header = {}
            self._begin_payload()

    def _on_header(self) -> None:
        self._header = json.loads(bytes(self._small_view[: self._hlen]))
        if getattr(self, "_oversize", False):
            self._fatal_oversize(self._header)
            return
        self._begin_payload()

    def _begin_payload(self) -> None:
        self._cur_sink = None
        if self._msg_type == wire.MSG_DATA and self._header.get("dlt") is None:
            self._cur_sink = self._server.peek_chunk_sink(
                (str(self._header.get("up")), str(self._header.get("down")))
            )
        if self._plen == 0:
            self._payload = bytearray(0)
            if self._flags & wire.FLAG_CRC_TRAILER:
                self._expect("trailer", 4)
            else:
                self._dispatch_frame()
            return
        self._payload = bytearray(self._plen)
        self._payload_view = memoryview(self._payload)
        self._payload_t0 = 0.0
        if self._plen >= _RAW_READ_BYTES:
            sock = (
                None
                if self._server._ssl_context is not None
                else self._transport.get_extra_info("socket")
            )
            if sock is not None:
                # Off-loop bulk read.  Safe w.r.t. buffering: get_buffer
                # windows are exact, so at this point the transport holds
                # no payload bytes — they're all still in the kernel.
                # State is "payload" for the whole drain (no protocol
                # callbacks fire while paused) so connection_lost's
                # mid-payload sink-abort applies to raw-read frames too.
                self._state = "payload"
                self._transport.pause_reading()
                self._payload_t0 = time.perf_counter()
                loop = asyncio.get_running_loop()
                fut = loop.run_in_executor(None, self._raw_read, sock.fileno())
                fut.add_done_callback(
                    lambda f: loop.call_soon_threadsafe(self._raw_read_done, f)
                )
                return
        self._expect("payload", self._plen)

    def _raw_read(self, fd: int) -> None:
        """Drain the payload into the preallocated buffer via os.readv on
        the raw fd (executor thread; the socket stays non-blocking —
        EAGAIN polls for readability).

        ``select.poll`` (not select) — no FD_SETSIZE limit — and an IDLE
        deadline (reset on every successful read) so a peer that
        declares a payload then stalls cannot pin a shared executor
        thread forever, while a slow-but-flowing large transfer is never
        cut off.
        """
        import os
        import select

        idle_limit = 120.0
        deadline = time.monotonic() + idle_limit
        poller = select.poll()
        poller.register(fd, select.POLLIN)
        view = self._payload_view
        src = self._header.get("src")
        got = 0
        while got < len(view):
            try:
                r = os.readv(fd, [view[got:]])
                if r == 0:
                    raise ConnectionError("peer closed mid-payload")
                got += r
                # Same liveness signal as the protocol path (note_rx_
                # progress tolerates this executor-thread caller).
                self._server.note_rx_progress(src, r)
                if self._cur_sink is not None:
                    try:  # sinks are thread-safe (see fl.streaming)
                        self._cur_sink.on_bytes(view, got)
                    except Exception:
                        logger.exception(
                            "[%s] chunk sink failed (raw read)",
                            self._server._party,
                        )
                        self._cur_sink = None
                deadline = time.monotonic() + idle_limit
            except (BlockingIOError, InterruptedError):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ConnectionError(
                        f"peer stalled mid-payload ({got}/{len(view)} bytes)"
                    )
                poller.poll(min(remaining, 10.0) * 1000)

    def _raw_read_done(self, fut) -> None:
        try:
            fut.result()
        except Exception as e:
            if not self._closed:
                logger.warning(
                    "[%s] payload read failed (peer=%s): %s",
                    self._server._party, self._peer, e,
                )
                self._abort()
            return
        if self._closed:
            return
        self._transport.resume_reading()
        self._got = self._need = self._plen  # state as if read via protocol
        self._state = "payload"
        if self._flags & wire.FLAG_CRC_TRAILER:
            self._expect("trailer", 4)
        else:
            self._dispatch_frame()

    def _on_payload(self) -> None:
        if self._flags & wire.FLAG_CRC_TRAILER:
            self._expect("trailer", 4)
            return
        self._dispatch_frame()

    def _on_trailer(self) -> None:
        import struct

        (self._trailer_crc,) = struct.unpack(">I", bytes(self._small_view[:4]))
        self._dispatch_frame()

    def _reset(self) -> None:
        self._payload = None
        self._payload_view = None
        self._cur_sink = None
        self._expect("prefix", _PREFIX_SIZE)

    # -- frame handling -------------------------------------------------------

    def _reply(self, msg_type: int, header: Dict[str, Any]) -> None:
        if self._transport is None or self._closed:
            return
        for buf in wire.pack_frame(msg_type, header):
            self._transport.write(buf)

    def _abort(self) -> None:
        if self._transport is not None:
            self._transport.close()
        self._closed = True

    def _fatal_oversize(self, header: Dict[str, Any]) -> None:
        self._reply(
            wire.MSG_ERR,
            {
                "rid": header.get("rid"),
                "fatal": True,
                "error": f"message of {self._plen} bytes exceeds max "
                f"{self._server._max_message_size}",
            },
        )
        # Close: the oversized payload is still in flight on the socket and
        # we refuse to buffer it.
        if self._transport is not None:
            # Give the reply a chance to flush before close.
            asyncio.get_running_loop().call_soon(self._abort)
        self._state = "drop"
        self._need = 1 << 62  # swallow whatever arrives until close

    def _dispatch_frame(self) -> None:
        server = self._server
        msg_type = self._msg_type
        header = self._header
        payload = self._payload if self._payload is not None else bytearray(0)
        read_seconds = (
            (time.perf_counter() - self._payload_t0) if self._payload_t0 else 0.0
        )
        trailer_crc = self._trailer_crc
        self._trailer_crc = None
        if trailer_crc is not None and "crc" not in header:
            header = dict(header, crc=trailer_crc)
        self._reset()

        if chaos.installed() is not None:
            # Chaos "wire" hook, receive side: covers EVERY frame type
            # (handshakes and pings included), so a partition rule also
            # starves the partner's health probes — to the sender this
            # party reads as dead while both processes stay alive.
            # Non-blocking variant: this is a sync protocol callback on
            # the shared event loop, so a delay rule must never sleep
            # here (it would stall every peer's frames, not one link's).
            try:
                chaos.fire_nonblocking(
                    "wire", party=server._party, src=header.get("src"),
                    type=msg_type,
                )
            except chaos.ChaosFault:
                # Discard without any reply: no ACK, no PONG — the
                # sender's deadline machinery is the point.  A sink that
                # already saw payload bytes hears a clean abort.
                if msg_type == wire.MSG_DATA:
                    self._notify_sink_abort(header, corrupt=False)
                return

        if msg_type == wire.MSG_HELLO:
            # Connection handshake (wire v4): a mixed-version pair must
            # fail HERE with a message naming both versions, not later
            # with a confusing manifest-decode error mid-payload.
            peer_ver = int(header.get("ver", 1))
            if peer_ver != wire.WIRE_FORMAT_VERSION:
                logger.warning(
                    "[%s] rejecting connection from %s: peer speaks wire "
                    "protocol v%s, this party speaks v%s",
                    server._party, header.get("src", self._peer),
                    peer_ver, wire.WIRE_FORMAT_VERSION,
                )
                self._reply(
                    wire.MSG_ERR,
                    {
                        "rid": header.get("rid"),
                        "fatal": True,
                        "code": "protocol",
                        "error": (
                            f"wire protocol version mismatch: peer "
                            f"{header.get('src', '?')!r} speaks "
                            f"v{peer_ver}, party {server._party!r} "
                            f"speaks v{wire.WIRE_FORMAT_VERSION} — "
                            f"upgrade the older party"
                        ),
                    },
                )
                # Flush the reply, then drop the connection.
                asyncio.get_running_loop().call_soon(self._abort)
                return
            reply = {
                "rid": header.get("rid"),
                "ver": wire.WIRE_FORMAT_VERSION,
                "src": server._party,
            }
            # Secure-aggregation key agreement rides the handshake
            # (transport/secagg.py): record the client's advertised key
            # and answer with our own, so one connection establishes
            # the pair's mask-seed state in both directions.
            sa = server.secagg
            if sa is not None:
                peer_adv = header.get(wire.SECAGG_PUB_KEY)
                src = header.get("src")
                if peer_adv and src:
                    sa.record_peer(str(src), peer_adv)
                reply[wire.SECAGG_PUB_KEY] = sa.hello_value()
            # Local-link colocation advertisement (transport/local.py):
            # always volunteered — three small strings per handshake.
            # The CLIENT decides whether to upgrade; a TLS listener
            # stays out of it (a link the operator encrypts must not
            # silently downgrade to an unencrypted AF_UNIX socket).
            if server._ssl_context is None:
                reply[wire.LOCAL_HOST_KEY] = local.host_identity()
                if server._local_sid is not None:
                    reply[wire.LOCAL_TOKEN_KEY] = local.endpoint_token(
                        server._local_sid
                    )
                if server._uds_path is not None:
                    reply[wire.LOCAL_UDS_KEY] = server._uds_path
            self._reply(wire.MSG_HELLO, reply)
            return
        if msg_type == wire.MSG_PING:
            self._reply(wire.MSG_PONG, {"rid": header.get("rid")})
            return
        if msg_type != wire.MSG_DATA:
            logger.warning(
                "[%s] unexpected frame type %s from %s",
                server._party, msg_type, self._peer,
            )
            self._abort()
            return

        if chaos.installed() is not None:
            try:
                # Same non-blocking discipline as the "wire" hook above:
                # this dispatch runs on the shared event loop.
                chaos.fire_nonblocking(
                    "server_frame", party=server._party,
                    src=header.get("src"), up=str(header.get("up")),
                    down=str(header.get("down")),
                )
            except chaos.ChaosFault:
                # Injected receive-side drop: discard the frame WITHOUT
                # an ACK — the sender's deadline/retry machinery is what
                # this fault exists to exercise.  A sink that saw the
                # payload's bytes hears a clean abort, like a died
                # connection.
                self._notify_sink_abort(header, corrupt=False)
                return

        if header.get("ccrc") is not None:
            # Stream frame (wire v3): per-chunk CRCs verified as the
            # integrity check — the whole-payload _crc_of re-check is
            # skipped (it would double-hash multi-GB payloads on the hot
            # receive path).  Delta frames also reconstruct against the
            # cached base here.
            self._handle_stream_data(header, payload, read_seconds)
            return

        expected_crc = header.get("crc")
        if expected_crc is not None:
            from rayfed_tpu import native

            if not native.is_available():
                # Advisory checksum: without the fast C++ path, verifying
                # at python speed would stall the pipeline — trust TCP.
                if not server._warned_no_native_crc:
                    server._warned_no_native_crc = True
                    logger.warning(
                        "[%s] peer sends checksums but native codec is "
                        "unavailable; skipping verification", server._party,
                    )
                expected_crc = None

        if expected_crc is not None and len(payload) >= _OFFLOAD_CRC_BYTES:
            # Big frame: verify off-loop; pause reading so per-connection
            # order holds without buffering unbounded frames.
            transport = self._transport
            if transport is not None:
                transport.pause_reading()
            loop = asyncio.get_running_loop()
            fut = loop.run_in_executor(None, _crc_of, payload)

            def _done(f):
                try:
                    actual = f.result()
                except Exception as e:  # pragma: no cover
                    logger.exception("[%s] crc executor error: %s", server._party, e)
                    self._abort()
                    return
                finally:
                    if transport is not None and not self._closed:
                        transport.resume_reading()
                self._finish_data(header, payload, read_seconds, expected_crc, actual)

            fut.add_done_callback(
                lambda f: loop.call_soon_threadsafe(_done, f)
            )
            return

        actual = None
        if expected_crc is not None:
            actual = _crc_of(payload)
        self._finish_data(header, payload, read_seconds, expected_crc, actual)

    def _handle_stream_data(self, header, payload, read_seconds) -> None:
        """Verify per-chunk CRCs and (for deltas) rebuild the full payload.

        Both are byte-bound work (CRC pass + a full-payload memcpy for
        deltas), so large frames run them off-loop with reading paused —
        same discipline as the whole-payload CRC offload."""
        server = self._server
        if header.get("stp") is not None:
            # Multi-rail stripe frame (wire v4): verify + place this
            # frame's chunks into the payload's reassembly buffer.
            # Other rails' frames keep flowing on their own
            # connections while this one verifies off-loop.  Keyed on
            # the LOGICAL total, not this frame's size: the group's
            # first frame allocates the whole assembly buffer (and for
            # deltas copies the cached base), and a short tail chunk
            # arriving first must not run that multi-GB byte work on
            # the event loop (same rule as the wire-v3 branch below).
            transport = self._transport
            _dlt = header.get("dlt") or {}
            big = max(
                len(payload), int(_dlt.get("total") or 0)
            ) >= _OFFLOAD_CRC_BYTES
            if big and transport is not None:
                transport.pause_reading()
            loop = asyncio.get_running_loop()
            if big:
                fut = loop.run_in_executor(
                    None, _apply_stripe_frame, server, header, payload,
                    read_seconds,
                )

                def _done(f):
                    try:
                        final, read_total = f.result()
                        exc = None
                    except Exception as e:
                        final, read_total, exc = None, read_seconds, e
                    finally:
                        if transport is not None and not self._closed:
                            transport.resume_reading()
                    self._stripe_result(header, read_total, final, exc)

                fut.add_done_callback(
                    lambda f: loop.call_soon_threadsafe(_done, f)
                )
                return
            try:
                final, read_total = _apply_stripe_frame(
                    server, header, payload, read_seconds
                )
                exc = None
            except Exception as e:
                final, read_total, exc = None, read_seconds, e
            self._stripe_result(header, read_total, final, exc)
            return
        dlt = header.get("dlt")
        total = int(dlt["total"]) if dlt else len(payload)
        if total >= _OFFLOAD_CRC_BYTES:
            transport = self._transport
            if transport is not None:
                transport.pause_reading()
            loop = asyncio.get_running_loop()
            fut = loop.run_in_executor(
                None, _verify_and_apply_stream, server, header, payload
            )

            def _done(f):
                try:
                    final = f.result()
                    exc = None
                except Exception as e:
                    final, exc = None, e
                finally:
                    if transport is not None and not self._closed:
                        transport.resume_reading()
                self._stream_result(header, read_seconds, final, exc)

            fut.add_done_callback(
                lambda f: loop.call_soon_threadsafe(_done, f)
            )
            return
        try:
            final = _verify_and_apply_stream(server, header, payload)
            exc = None
        except Exception as e:
            final, exc = None, e
        self._stream_result(header, read_seconds, final, exc)

    def _notify_sink_abort(self, header, corrupt: bool) -> None:
        """A frame that fed a chunk sink failed verification (or died):
        the sink must know, so already-folded bytes don't silently
        survive into the aggregate when the sender retries."""
        sink = self._server.peek_chunk_sink(
            (str(header.get("up")), str(header.get("down")))
        )
        if sink is not None:
            try:
                sink.on_frame_abort(corrupt=corrupt)
            except Exception:  # pragma: no cover - sink bug
                logger.exception(
                    "[%s] chunk sink abort failed", self._server._party
                )

    def _stripe_result(self, header, read_seconds, final, exc) -> None:
        """Reply for one stripe frame: SEG while the payload assembles,
        the ordinary delivery path on completion, errors as MSG_ERR."""
        server = self._server
        if exc is not None:
            if isinstance(exc, _DeltaBaseMissing):
                server.stats["receive_delta_base_misses"] = (
                    server.stats.get("receive_delta_base_misses", 0) + 1
                )
                self._reply(
                    wire.MSG_ERR,
                    {
                        "rid": header.get("rid"),
                        "code": "delta_base",
                        "error": str(exc),
                    },
                )
                return
            if isinstance(exc, _StripeFatal):
                # Non-transient (oversize): abort the send instead of
                # letting the retry policy re-ship the whole payload.
                self._notify_sink_abort(header, corrupt=False)
                self._reply(
                    wire.MSG_ERR,
                    {
                        "rid": header.get("rid"),
                        "fatal": True,
                        "error": str(exc),
                    },
                )
                return
            if isinstance(exc, _StripeReject):
                # Protocol-state reject (stale sid / evicted assembly /
                # geometry): NOT corruption — its own counter, so an
                # eviction burst can't read as phantom CRC errors.
                server.stats["receive_stripe_rejects"] = (
                    server.stats.get("receive_stripe_rejects", 0) + 1
                )
                self._notify_sink_abort(header, corrupt=False)
                self._reply(
                    wire.MSG_ERR,
                    {
                        "rid": header.get("rid"),
                        "error": f"stripe frame rejected: {exc}",
                    },
                )
                return
            server.stats["receive_crc_errors"] = (
                server.stats.get("receive_crc_errors", 0) + 1
            )
            # Clean abort, never corrupt: a sink only ever saw VERIFIED
            # prefix bytes (identical on the sender's full retry), so
            # its folded blocks stay a valid prefix — reset-and-retry,
            # not the unrecoverable donated-accumulator failure.
            self._notify_sink_abort(header, corrupt=False)
            self._reply(
                wire.MSG_ERR,
                {
                    "rid": header.get("rid"),
                    "error": f"stripe frame verification failed: {exc}",
                },
            )
            return
        if final is None:
            self._reply(
                wire.MSG_ACK, {"rid": header.get("rid"), "result": "SEG"}
            )
            return
        self._finish_data(header, final, read_seconds, None, None)

    def _stream_result(self, header, read_seconds, final, exc) -> None:
        server = self._server
        if exc is not None:
            if isinstance(exc, _DeltaBaseMissing):
                server.stats["receive_delta_base_misses"] = (
                    server.stats.get("receive_delta_base_misses", 0) + 1
                )
                self._reply(
                    wire.MSG_ERR,
                    {
                        "rid": header.get("rid"),
                        "code": "delta_base",
                        "error": str(exc),
                    },
                )
                return
            server.stats["receive_crc_errors"] = (
                server.stats.get("receive_crc_errors", 0) + 1
            )
            self._notify_sink_abort(header, corrupt=True)
            self._reply(
                wire.MSG_ERR,
                {
                    "rid": header.get("rid"),
                    "error": f"stream payload verification failed: {exc}",
                },
            )
            return
        self._finish_data(header, final, read_seconds, None, None)

    def _finish_data(
        self, header, payload, read_seconds, expected_crc, actual
    ) -> None:
        server = self._server
        if expected_crc is not None and actual != expected_crc:
            server.stats["receive_crc_errors"] = (
                server.stats.get("receive_crc_errors", 0) + 1
            )
            self._notify_sink_abort(header, corrupt=True)
            self._reply(
                wire.MSG_ERR,
                {
                    "rid": header.get("rid"),
                    "error": f"payload checksum mismatch "
                    f"({actual:#x} != {expected_crc:#x})",
                },
            )
            return
        ep = (header.get("meta") or {}).get(wire.EPOCH_TAG_KEY)
        if ep is not None and server.epoch_provider is not None:
            cur = server.epoch_provider()
            if cur is not None and int(ep) < int(cur):
                # STALE-epoch frame (elastic membership): the sender's
                # roster lags this party's — reject LOUDLY and fatally
                # (a retry can't fix a stale epoch; the late
                # contribution folds into the next round via the
                # sender's own DGA correction instead).  Frames from a
                # NEWER epoch are accepted: a straggler a full round
                # behind still has the old epoch when the advanced
                # coordinator's broadcast lands, and that broadcast is
                # the very frame carrying the roster transition it
                # needs — gating it would strand every straggler.
                server.stats["receive_epoch_rejects"] = (
                    server.stats.get("receive_epoch_rejects", 0) + 1
                )
                logger.warning(
                    "[%s] rejecting frame (%s, %s) from %s: roster epoch "
                    "%s, this party is at epoch %s",
                    server._party, header.get("up"), header.get("down"),
                    header.get("src"), ep, cur,
                )
                self._notify_sink_abort(header, corrupt=False)
                self._reply(
                    wire.MSG_ERR,
                    {
                        "rid": header.get("rid"),
                        "fatal": True,
                        "code": "epoch",
                        "error": (
                            f"stale roster epoch: frame carries epoch "
                            f"{ep}, party {server._party!r} is at epoch "
                            f"{cur} — the membership advanced; fold the "
                            f"late contribution into the next round"
                        ),
                    },
                )
                return
        message = Message(
            src_party=header.get("src", "?"),
            upstream_seq_id=str(header.get("up")),
            downstream_seq_id=str(header.get("down")),
            payload=payload,
            metadata=header.get("meta", {}),
            read_seconds=read_seconds,
            error=header.get("err"),
        )
        server.stats["receive_op_count"] += 1
        server.stats["receive_bytes"] += len(payload)
        _tr = telemetry.active()
        if _tr is not None:
            # Server-side delivery record: a verified payload is about
            # to reach its consumer (observer, chunk sink, or mailbox).
            # This is a sync event-loop callback — the emit is a ring
            # append, never a sleep (the fire_nonblocking discipline).
            meta = header.get("meta") or {}
            rnd = meta.get(wire.ROUND_TAG_KEY)
            _tr.emit(
                "wire.deliver", party=server._party,
                peer=message.src_party,
                stream=message.upstream_seq_id,
                nbytes=len(payload),
                t_start=time.time() - float(read_seconds or 0.0),
                dur_s=float(read_seconds or 0.0),
                round=int(rnd) if rnd is not None else None,
                epoch=int(ep) if ep is not None else None,
                outcome="error" if message.error is not None else "ok",
            )
        key = (message.upstream_seq_id, message.downstream_seq_id)
        for obs in list(server._observers):
            try:
                if obs(message):
                    # Consumed by a control-plane observer (e.g. a
                    # roster membership request): never enters the
                    # mailbox, but the rendezvous is still remembered
                    # (sender retries dedupe) and the delivery counts
                    # as liveness.
                    server._mailbox.mark_delivered(message.src_party, key)
                    self._reply(
                        wire.MSG_ACK,
                        {"rid": header.get("rid"), "result": "OK"},
                    )
                    return
            except Exception:  # pragma: no cover - observer bug
                logger.exception(
                    "[%s] message observer failed", server._party
                )
        sink = server.take_chunk_sink(key)
        if sink is not None:
            # Sink-consumed delivery: the payload never parks in the
            # mailbox (the streaming aggregator already folded it in, or
            # takes it whole here) — but the rendezvous is still marked
            # consumed so a sender retry after a lost ACK is deduped,
            # and the delivery still counts as peer liveness.
            server._mailbox.mark_delivered(message.src_party, key)
            try:
                if message.error is not None:
                    sink.on_error(message.error)
                else:
                    sink.on_complete(message.payload)
            except Exception:
                logger.exception(
                    "[%s] chunk sink completion failed", server._party
                )
            self._reply(
                wire.MSG_ACK, {"rid": header.get("rid"), "result": "OK"}
            )
            return
        if server._on_message is not None:
            server._on_message(message)
        server._mailbox.put(message)
        self._reply(wire.MSG_ACK, {"rid": header.get("rid"), "result": "OK"})


def _crc_of(payload) -> int:
    from rayfed_tpu import native

    return native.crc32c(payload)


def _verify_and_apply_stream(server: "TransportServer", header, payload):
    """Verify a stream frame's per-chunk CRCs; rebuild deltas on the base.

    Executor-thread safe (pure byte work + the server's delta-base lock).
    Returns the FULL logical payload: the frame's own payload for full
    sends, or a fresh buffer with the changed chunks overlaid on the
    cached base for delta frames.  The result is stored as the stream's
    new base — never mutated in place afterwards, so zero-copy decode
    views of a delivered payload stay valid.
    """
    import zlib

    csz = int(header.get("ccsz") or wire.DELTA_CHUNK_BYTES)
    ccrc = header["ccrc"]
    dlt = header.get("dlt")
    src = header.get("src", "?")
    stm = header.get("stm", "?")
    mv = memoryview(payload)

    if dlt is None:
        nch = max(1, -(-len(mv) // csz))
        if len(ccrc) != nch:
            raise ValueError(
                f"{len(ccrc)} chunk CRCs for {nch} payload chunks"
            )
        for i, expect in enumerate(ccrc):
            if zlib.crc32(mv[i * csz : (i + 1) * csz]) != expect:
                raise ValueError(f"chunk {i} CRC mismatch")
        server._store_delta_base(
            src, stm, payload, list(ccrc), wire.crc_fingerprint(ccrc)
        )
        return payload

    total = int(dlt["total"])
    nch = max(1, -(-total // csz))
    indices = wire.decode_chunk_bitmap(dlt["map"], nch)
    if len(indices) != len(ccrc):
        raise ValueError(
            f"delta bitmap selects {len(indices)} chunks but "
            f"{len(ccrc)} CRCs were sent"
        )
    base = server._get_delta_base(src, stm)
    if base is None:
        raise _DeltaBaseMissing(
            f"no cached base for stream {stm!r} from {src!r}"
        )
    if len(base["data"]) != total or base["fp"] != int(dlt["bfp"]):
        raise _DeltaBaseMissing(
            f"cached base for stream {stm!r} from {src!r} desynced "
            f"(restart or lost update)"
        )
    if not indices:
        # Byte-identical resend (the cache's best case): the stored base
        # IS the payload — no O(model) copy, no re-store (bases are
        # never mutated in place, so sharing it with the consumer is
        # safe).
        if len(mv):
            raise ValueError("empty delta bitmap with a non-empty payload")
        server.stats["receive_delta_frames"] = (
            server.stats.get("receive_delta_frames", 0) + 1
        )
        server.stats["receive_delta_bytes_saved"] = (
            server.stats.get("receive_delta_bytes_saved", 0) + total
        )
        return base["data"]
    new = bytearray(base["data"])
    new_ccrc = list(base["ccrc"])
    off = 0
    for i, expect in zip(indices, ccrc):
        size = min(csz, total - i * csz)
        chunk = mv[off : off + size]
        if len(chunk) != size:
            raise ValueError("delta payload shorter than its bitmap")
        if zlib.crc32(chunk) != expect:
            raise ValueError(f"delta chunk {i} CRC mismatch")
        new[i * csz : i * csz + size] = chunk
        new_ccrc[i] = expect
        off += size
    if off != len(mv):
        raise ValueError(
            f"delta payload has {len(mv) - off} trailing bytes"
        )
    server._store_delta_base(
        src, stm, new, new_ccrc, wire.crc_fingerprint(new_ccrc)
    )
    server.stats["receive_delta_frames"] = (
        server.stats.get("receive_delta_frames", 0) + 1
    )
    server.stats["receive_delta_bytes_saved"] = (
        server.stats.get("receive_delta_bytes_saved", 0) + total - len(mv)
    )
    return new


def _apply_stripe_frame(
    server: "TransportServer", header, payload, read_seconds
):
    """Verify and place one stripe frame's chunks (wire v4).

    Returns ``(full_payload, read_s_total)`` when the frame completes
    its payload's reassembly, ``(None, read_seconds)`` while partial.
    Executor-thread safe: frames of one payload arrive concurrently on
    different rail connections — the assembly map is guarded by the
    server's stripe lock, chunk placement by the per-assembly lock.

    A frame whose ``sid`` is newer than the pending assembly's replaces
    it (the sender's retry re-ships the whole payload under a fresh
    sid); an older ``sid`` is a stale frame of a failed attempt and is
    rejected.  Fresh payloads additionally feed any registered chunk
    sink their growing contiguous VERIFIED prefix, so streaming
    aggregation keeps overlapping the wire under shuffled arrival.
    """
    import zlib

    stp = header["stp"]
    dlt = header["dlt"]
    src = header.get("src", "?")
    stm = header.get("stm")
    sid = int(stp["sid"])
    nf = int(stp["nf"])
    total = int(dlt["total"])
    csz = int(header.get("ccsz") or wire.DELTA_CHUNK_BYTES)
    nch = max(1, -(-total // csz))
    key = (src, str(header.get("up")), str(header.get("down")))
    is_delta = "bfp" in dlt

    with server._stripe_lock:
        now = time.monotonic()
        for k in list(server._stripes):  # drop abandoned assemblies
            if now - server._stripes[k].touched > _STRIPE_IDLE_DROP_S:
                server._note_stripe_evicted(k, server._stripes[k].sid)
                del server._stripes[k]
        asm = server._stripes.get(key)
        if asm is not None and sid < asm.sid:
            raise _StripeReject(
                f"stale stripe frame (sid {sid} < current {asm.sid})"
            )
        if asm is None and (key, sid) in server._stripe_evicted:
            # A continuation frame of a group whose assembly was
            # evicted: recreating it would restart the frame counter
            # and the group could never complete (every rail would ACK
            # SEG forever).  Fail the frame so the sender drains its
            # rails and re-ships the payload under a fresh sid.
            raise _StripeReject(
                f"stripe assembly (sid {sid}) was dropped under memory "
                f"pressure before this frame arrived; re-send the payload"
            )
        if asm is None or sid > asm.sid:
            if total > server._max_message_size:
                raise _StripeFatal(
                    f"striped message of {total} bytes exceeds max "
                    f"{server._max_message_size}"
                )
            if is_delta:
                if stm is None:
                    raise ValueError("delta stripe frame without a stream")
                base = server._get_delta_base(src, stm)
                if base is None:
                    raise _DeltaBaseMissing(
                        f"no cached base for stream {stm!r} from {src!r}"
                    )
                if len(base["data"]) != total or base["fp"] != int(dlt["bfp"]):
                    raise _DeltaBaseMissing(
                        f"cached base for stream {stm!r} from {src!r} "
                        f"desynced (restart or lost update)"
                    )
                buf = bytearray(base["data"])
                ccrc = list(base["ccrc"])
            else:
                buf = bytearray(total)
                ccrc = [0] * nch
            asm = _StripeAsm(sid, total, csz, nch, nf, buf, ccrc, is_delta)
            server._stripes[key] = asm
            server._stripes.move_to_end(key)
            while len(server._stripes) > _MAX_STRIPE_ASM:
                old_key, old_asm = server._stripes.popitem(last=False)
                # The evicted group can never complete now — remember
                # it so its remaining frames error (sender retries)
                # instead of silently recreating a counter that never
                # reaches nf.
                server._note_stripe_evicted(old_key, old_asm.sid)
        else:
            server._stripes.move_to_end(key)
        asm.touched = now

    try:
        if (
            asm.total != total or asm.csz != csz or asm.nf != nf
            or asm.is_delta != is_delta
        ):
            raise _StripeReject("stripe frames disagree on payload geometry")
        indices = wire.decode_chunk_bitmap(dlt["map"], nch)
        ccrc_hdr = header["ccrc"]
        if len(indices) != len(ccrc_hdr):
            raise ValueError(
                f"stripe bitmap selects {len(indices)} chunks but "
                f"{len(ccrc_hdr)} CRCs were sent"
            )
        mv = memoryview(payload)
        with asm.lock:
            off = 0
            for i, expect in zip(indices, ccrc_hdr):
                size = min(csz, total - i * csz)
                chunk = mv[off : off + size]
                if len(chunk) != size:
                    raise ValueError("stripe payload shorter than its bitmap")
                if zlib.crc32(chunk) != expect:
                    raise ValueError(f"stripe chunk {i} CRC mismatch")
                asm.buf[i * csz : i * csz + size] = chunk
                asm.ccrc[i] = expect
                asm.have.add(i)
                off += size
            if off != len(mv):
                raise ValueError(
                    f"stripe payload has {len(mv) - off} trailing bytes"
                )
            asm.frames += 1
            asm.shipped += len(mv)
            asm.read_s += read_seconds
            complete = asm.frames >= asm.nf
            if complete and not asm.is_delta and len(asm.have) != nch:
                raise ValueError(
                    f"stripe group complete with {len(asm.have)}/{nch} chunks"
                )
            feed_to = 0
            if not asm.is_delta:
                while asm.prefix in asm.have:
                    asm.prefix += 1
                feed_to = min(asm.prefix * csz, total)
    except Exception:
        # A bad frame kills the whole assembly: the sender fails the
        # payload as a unit and re-ships it under a fresh sid.  Mark it
        # evicted so sibling frames still in flight on other rails fail
        # fast instead of recreating a counter that can't complete.
        with server._stripe_lock:
            if server._stripes.get(key) is asm:
                server._note_stripe_evicted(key, asm.sid)
                del server._stripes[key]
        raise

    if not complete:
        if feed_to:
            sink = server.peek_chunk_sink(
                (str(header.get("up")), str(header.get("down")))
            )
            if sink is not None:
                try:  # sinks are thread-safe (see fl.streaming)
                    sink.on_bytes(memoryview(asm.buf), feed_to)
                except Exception:
                    logger.exception(
                        "[%s] chunk sink failed (stripe feed)",
                        server._party,
                    )
        return None, read_seconds

    with server._stripe_lock:
        if server._stripes.get(key) is asm:
            del server._stripes[key]
    if stm is not None:
        server._store_delta_base(
            src, stm, asm.buf, asm.ccrc, wire.crc_fingerprint(asm.ccrc)
        )
    server.stats["receive_stripe_frames"] = (
        server.stats.get("receive_stripe_frames", 0) + asm.frames
    )
    server.stats["receive_striped_payloads"] = (
        server.stats.get("receive_striped_payloads", 0) + 1
    )
    if asm.is_delta:
        server.stats["receive_delta_frames"] = (
            server.stats.get("receive_delta_frames", 0) + 1
        )
        server.stats["receive_delta_bytes_saved"] = (
            server.stats.get("receive_delta_bytes_saved", 0)
            + total - asm.shipped
        )
    _tr = telemetry.active()
    if _tr is not None:
        # Multi-rail reassembly completed: one record per striped
        # payload with how many cross-rail frames built it and how many
        # bytes actually crossed the wire (delta stripes overlay a
        # cached base).  Ring append only — may run on the loop.
        _tr.emit(
            "wire.reassemble", party=server._party, peer=src,
            stream=stm, nbytes=total,
            t_start=time.time() - asm.read_s, dur_s=asm.read_s,
            detail={
                "frames": asm.frames, "shipped_bytes": asm.shipped,
                "delta": bool(asm.is_delta),
            },
        )
    return asm.buf, asm.read_s


class TransportServer:
    def __init__(
        self,
        party: str,
        listen_addr: str,
        mailbox: Mailbox,
        max_message_size: int,
        ssl_context: Optional[ssl.SSLContext] = None,
        on_message: Optional[Callable[[Message], None]] = None,
    ) -> None:
        self._party = party
        host, _, port = listen_addr.rpartition(":")
        self._host = host or "0.0.0.0"
        self._port = int(port)
        self._mailbox = mailbox
        self._max_message_size = max_message_size
        self._ssl_context = ssl_context
        self._server: Optional[asyncio.AbstractServer] = None
        self._on_message = on_message
        # Consuming observers (loop thread): each is called with every
        # delivered DATA message BEFORE the mailbox; returning True
        # consumes it (no mailbox entry, still ACKed + liveness-
        # credited).  The control-plane demux the roster membership
        # inbox rides on — unlike _on_message (the multi-host leader's
        # republish tap), observers may be stacked.
        self._observers: list = []
        # Elastic membership: () -> Optional[int], the receiver's
        # current roster epoch.  Frames stamped with a different epoch
        # (wire.EPOCH_TAG_KEY) are rejected loudly.  Set by the manager.
        self.epoch_provider: Optional[Callable[[], Optional[int]]] = None
        # Secure-aggregation key agreement (transport/secagg.py): when
        # set by the manager, inbound HELLOs have their key
        # advertisement recorded and the HELLO reply carries ours.
        self.secagg: Optional[Any] = None
        self._warned_no_native_crc = False
        self.stats: Dict[str, Any] = {"receive_op_count": 0, "receive_bytes": 0}
        # Per-party monotonically growing byte counters INCLUDING bytes
        # of payloads still in flight (the completed-put counters above
        # only move at frame boundaries).  Written from the loop thread
        # and the raw-read executor threads: plain dict ops are atomic
        # under the GIL, and a (rare) lost += only delays the health
        # monitor's liveness credit by one ping cycle.
        self._rx_progress: Dict[str, int] = {}
        # Delta bases: (src, stream) → last full payload + its chunk
        # CRCs + fingerprint.  Touched from the loop thread and the
        # stream-verify executor jobs, hence the lock; bounded LRU.
        self._delta_lock = threading.Lock()
        self._delta_bases: "collections.OrderedDict[Tuple[str, str], Dict]" = (
            collections.OrderedDict()
        )
        # Multi-rail stripe reassemblies (wire v4): rendezvous key →
        # in-progress _StripeAsm.  Touched from several executor
        # threads concurrently (one per rail connection) — the map is
        # guarded here, chunk placement by each assembly's own lock.
        self._stripe_lock = threading.Lock()
        self._stripes: "collections.OrderedDict[Tuple[str, str, str], _StripeAsm]" = (
            collections.OrderedDict()
        )
        # (key, sid) pairs whose in-progress assembly was evicted (LRU
        # pressure / idle drop): their continuation frames must error —
        # recreating the assembly would restart the frame counter and
        # the group could never complete.  Bounded ring; guarded by
        # _stripe_lock.
        self._stripe_evicted: "collections.OrderedDict[Tuple, None]" = (
            collections.OrderedDict()
        )
        # Chunk sinks: (up, down) → streaming consumer (loop thread
        # only; registered by TransportManager.recv_stream).
        self._chunk_sinks: Dict[Tuple[str, str], Any] = {}
        # Live connections (loop thread only): stop() aborts them so
        # peers see EOF promptly instead of half-open sockets.
        self._protocols: set = set()
        # Local-link fast path (transport/local.py): the AF_UNIX twin
        # listener (same frames, same dispatch — just not the loopback
        # TCP stack) and this server's in-process registry id, both
        # advertised in HELLO replies so colocated clients can upgrade.
        self._uds_path: Optional[str] = None
        self._uds_server: Optional[asyncio.AbstractServer] = None
        self._local_sid: Optional[str] = None

    def _note_stripe_evicted(self, key, sid: int) -> None:
        """Record an evicted in-progress stripe group (caller holds
        ``_stripe_lock``)."""
        self._stripe_evicted[(key, sid)] = None
        while len(self._stripe_evicted) > 4 * _MAX_STRIPE_ASM:
            self._stripe_evicted.popitem(last=False)

    def note_rx_progress(self, party: Optional[str], nbytes: int) -> None:
        if party:
            self._rx_progress[party] = self._rx_progress.get(party, 0) + nbytes

    def receive_progress(self) -> Dict[str, int]:
        """Snapshot of per-party received bytes (incl. in-flight payloads)."""
        return dict(self._rx_progress)

    # -- delta base cache (wire v3 streams) -----------------------------------

    def _get_delta_base(self, src: str, stream: str) -> Optional[Dict]:
        with self._delta_lock:
            entry = self._delta_bases.get((src, stream))
            if entry is not None:
                self._delta_bases.move_to_end((src, stream))
            return entry

    def _store_delta_base(
        self, src: str, stream: str, data, ccrc, fp: int
    ) -> None:
        with self._delta_lock:
            self._delta_bases[(src, stream)] = {
                "data": data, "ccrc": ccrc, "fp": fp,
            }
            self._delta_bases.move_to_end((src, stream))
            while len(self._delta_bases) > _MAX_DELTA_BASES:
                self._delta_bases.popitem(last=False)

    # -- chunk sinks (streaming aggregation) ----------------------------------

    def register_chunk_sink(self, key: Tuple[str, str], sink: Any) -> None:
        """Attach a streaming consumer to one (up, down) rendezvous.

        The sink sees ``on_bytes(view, total)`` as payload bytes land
        (loop thread or raw-read executor thread — must be thread-safe),
        then exactly one of ``on_complete(payload)`` / ``on_error(err)``
        on the loop thread; the frame bypasses the mailbox.  A frame
        that dies before delivery — connection lost mid-payload, or
        verification failure — instead emits ``on_frame_abort(corrupt=
        bool)`` and the sink stays registered for the sender's retry.
        Loop-thread only (TransportManager schedules it)."""
        self._chunk_sinks[key] = sink

    def unregister_chunk_sink(self, key: Tuple[str, str]) -> None:
        self._chunk_sinks.pop(key, None)

    def peek_chunk_sink(self, key: Tuple[str, str]):
        return self._chunk_sinks.get(key)

    def take_chunk_sink(self, key: Tuple[str, str]):
        return self._chunk_sinks.pop(key, None)

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._server = await loop.create_server(
            lambda: _FrameProtocol(self),
            host=self._host,
            port=self._port,
            ssl=self._ssl_context,
        )
        if self._port == 0:  # OS-assigned (bridge listeners)
            self._port = self._server.sockets[0].getsockname()[1]
        if self._ssl_context is None:
            # AF_UNIX twin listener (local-link fast path): same
            # protocol, advertised in HELLO replies.  Best-effort — a
            # host without a writable tmpdir just never advertises one,
            # and clients keep TCP.  TLS listeners opt out entirely (an
            # encrypted link must not downgrade to a plain socket).
            path = local.make_uds_path()
            try:
                self._uds_server = await loop.create_unix_server(
                    lambda: _FrameProtocol(self), path
                )
                self._uds_path = path
            except (OSError, NotImplementedError) as e:
                logger.debug(
                    "[%s] no AF_UNIX twin listener: %s", self._party, e
                )
            # In-process registry: colocated clients in THIS interpreter
            # discover the server object itself (shared-memory handoff)
            # without a probe connection.
            self._local_sid = local.register_server(
                self, loop, self._host, self._port
            )
        logger.debug("[%s] transport server listening on %s:%s",
                     self._party, self._host, self._port)

    @property
    def bound_port(self) -> int:
        return self._port

    async def stop(self) -> None:
        local.unregister_server(self._local_sid)
        self._local_sid = None
        if self._uds_server is not None:
            self._uds_server.close()
            await self._uds_server.wait_closed()
            self._uds_server = None
        if self._uds_path is not None:
            try:
                import os

                os.unlink(self._uds_path)
            except OSError:
                pass
            self._uds_path = None
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        # Server.close() only stops the LISTENER; established
        # connections would linger half-open (a peer's in-flight send
        # then waits out its full ACK deadline instead of seeing EOF
        # and reconnecting).  Abort them explicitly.
        for proto in list(self._protocols):
            proto._abort()
        self._protocols.clear()
