"""Asyncio transport server — the receive side of the push transport.

Plays the role of the reference's ``RecverProxyActor`` gRPC server
(``barriers.py:93-118, 280-351``) without an actor framework: one listener
per party, frames demuxed into the rendezvous :class:`Mailbox`.  TLS
(including mutual auth) is plain ``ssl`` on the listener.
"""

from __future__ import annotations

import asyncio
import json
import logging
import ssl
import time
from typing import Any, Callable, Dict, Optional

from rayfed_tpu.transport import wire
from rayfed_tpu.transport.rendezvous import Mailbox, Message

logger = logging.getLogger(__name__)


class TransportServer:
    def __init__(
        self,
        party: str,
        listen_addr: str,
        mailbox: Mailbox,
        max_message_size: int,
        ssl_context: Optional[ssl.SSLContext] = None,
        on_message: Optional[Callable[[Message], None]] = None,
    ) -> None:
        self._party = party
        host, _, port = listen_addr.rpartition(":")
        self._host = host or "0.0.0.0"
        self._port = int(port)
        self._mailbox = mailbox
        self._max_message_size = max_message_size
        self._ssl_context = ssl_context
        self._server: Optional[asyncio.AbstractServer] = None
        self._on_message = on_message
        self._warned_no_native_crc = False
        self.stats: Dict[str, Any] = {"receive_op_count": 0, "receive_bytes": 0}

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_connection,
            host=self._host,
            port=self._port,
            ssl=self._ssl_context,
            limit=2**20,
        )
        logger.debug("[%s] transport server listening on %s:%s",
                     self._party, self._host, self._port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        try:
            while True:
                try:
                    prefix = await reader.readexactly(wire.HEADER_SIZE)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break
                msg_type, _flags, hlen, plen = wire.unpack_frame_prefix(prefix)
                header = json.loads(await reader.readexactly(hlen)) if hlen else {}
                if plen > self._max_message_size:
                    # Fatal (non-retryable): drain and drop the payload so the
                    # sender's write never blocks on a full TCP buffer, then
                    # echo rid so the client matches the pending send.
                    remaining = plen
                    while remaining:
                        chunk = await reader.read(min(1 << 20, remaining))
                        if not chunk:
                            break
                        remaining -= len(chunk)
                    await self._reply(
                        writer, wire.MSG_ERR,
                        {"rid": header.get("rid"), "fatal": True,
                         "error": f"message of {plen} bytes exceeds max "
                                  f"{self._max_message_size}"},
                    )
                    break
                t_read = time.perf_counter()
                payload = await reader.readexactly(plen) if plen else b""
                read_seconds = time.perf_counter() - t_read

                expected_crc = header.get("crc")
                if expected_crc is not None and msg_type == wire.MSG_DATA:
                    from rayfed_tpu import native

                    if not native.is_available():
                        # The crc header is advisory: without the fast C++
                        # path, verifying at ~MB/s python speed would stall
                        # this connection — trust TCP integrity instead.
                        if not self._warned_no_native_crc:
                            self._warned_no_native_crc = True
                            logger.warning(
                                "[%s] peer sends checksums but native codec "
                                "is unavailable; skipping verification",
                                self._party,
                            )
                        expected_crc = None
                if expected_crc is not None and msg_type == wire.MSG_DATA:
                    from rayfed_tpu import native

                    # Off-loop so a multi-MB checksum never blocks other
                    # connections' frames (per-connection order is kept —
                    # we await before reading the next frame).
                    actual = await asyncio.get_running_loop().run_in_executor(
                        None, native.crc32c, payload
                    )
                    if actual != expected_crc:
                        # Retryable: corruption is transient; the sender's
                        # retry policy re-pushes the frame.
                        self.stats["receive_crc_errors"] = (
                            self.stats.get("receive_crc_errors", 0) + 1
                        )
                        await self._reply(
                            writer, wire.MSG_ERR,
                            {"rid": header.get("rid"),
                             "error": f"payload checksum mismatch "
                                      f"({actual:#x} != {expected_crc:#x})"},
                        )
                        continue

                if msg_type == wire.MSG_DATA:
                    message = Message(
                        src_party=header.get("src", "?"),
                        upstream_seq_id=str(header.get("up")),
                        downstream_seq_id=str(header.get("down")),
                        payload=payload,
                        metadata=header.get("meta", {}),
                        read_seconds=read_seconds,
                    )
                    self.stats["receive_op_count"] += 1
                    self.stats["receive_bytes"] += len(payload)
                    if self._on_message is not None:
                        self._on_message(message)
                    self._mailbox.put(message)
                    await self._reply(
                        writer, wire.MSG_ACK, {"rid": header.get("rid"), "result": "OK"}
                    )
                elif msg_type == wire.MSG_PING:
                    await self._reply(writer, wire.MSG_PONG, {"rid": header.get("rid")})
                else:
                    logger.warning("[%s] unexpected frame type %s from %s",
                                   self._party, msg_type, peer)
                    break
        except Exception:  # pragma: no cover - connection-level robustness
            logger.exception("[%s] connection handler error (peer=%s)",
                             self._party, peer)
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _reply(self, writer: asyncio.StreamWriter, msg_type: int,
                     header: Dict[str, Any]) -> None:
        for buf in wire.pack_frame(msg_type, header):
            writer.write(buf)
        await writer.drain()
