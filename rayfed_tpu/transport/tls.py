"""TLS/mTLS context construction from the reference's tls_config shape.

``tls_config = {"ca_cert": <path>, "cert": <path>, "key": <path>}``
(reference ``fed/utils.py:114-128``).  Both directions authenticate: the
server requires a client certificate signed by the shared CA (the
reference enables mutual TLS on its gRPC channels the same way).
"""

from __future__ import annotations

import ssl
from typing import Dict, Optional


def validate_tls_config(tls_config: Dict[str, str]) -> None:
    if not tls_config:
        return
    missing = {"ca_cert", "cert", "key"} - set(tls_config)
    if missing:
        raise ValueError(f"tls_config missing required keys: {sorted(missing)}")


def server_ssl_context(tls_config: Optional[Dict[str, str]]) -> Optional[ssl.SSLContext]:
    if not tls_config:
        return None
    validate_tls_config(tls_config)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile=tls_config["cert"], keyfile=tls_config["key"])
    ctx.load_verify_locations(cafile=tls_config["ca_cert"])
    ctx.verify_mode = ssl.CERT_REQUIRED  # mutual TLS
    return ctx


def client_ssl_context(tls_config: Optional[Dict[str, str]]) -> Optional[ssl.SSLContext]:
    if not tls_config:
        return None
    validate_tls_config(tls_config)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.load_verify_locations(cafile=tls_config["ca_cert"])
    ctx.load_cert_chain(certfile=tls_config["cert"], keyfile=tls_config["key"])
    # Cross-silo peers are addressed by IP from a private cluster map; the
    # CA is the trust anchor, not DNS naming.
    ctx.check_hostname = False
    return ctx
