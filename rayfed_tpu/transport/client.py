"""Transport client — pooled, multiplexed, retrying connections per peer.

Plays the role of the reference's ``send_data_grpc`` channel
(``barriers.py:121-181``) plus its gRPC service-config retry policy
(``grpc_options.py:17-23``): attempts with exponential backoff on
transport unavailability, a per-RPC deadline, per-party metadata headers,
and a message-size cap.

Data plane: a small pool of connections per destination (concurrent
pushes to the same party ride different sockets instead of queuing behind
one write lock — no per-peer head-of-line blocking), and payload bytes
go to the kernel through the native vectored-write path
(``native.writev_full``: C++ writev with the GIL released) off the event
loop — no copy into asyncio's transport buffer.  TLS connections fall
back to the asyncio writer (the SSL layer owns the socket).  ACKs are
matched by request id on each connection's reader task.
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import json
import logging
import ssl
import struct
import time
from typing import Any, Dict, List, Optional

from rayfed_tpu import chaos
from rayfed_tpu import telemetry
from rayfed_tpu.config import RetryPolicy
from rayfed_tpu.transport import local
from rayfed_tpu.transport import wire

logger = logging.getLogger(__name__)

# Streamed payload bytes are cut into chunks of this size on the write
# path: the CRC of chunk k+1 (and the device→host fetch of the next
# lazy shard) runs in an executor thread while chunk k's writev blocks
# in another — the socket never waits on checksum/encode work and vice
# versa.  4 MB rides well above syscall overhead while keeping ~2 chunks
# of lookahead memory.
WRITE_CHUNK_BYTES = 4 * 1024 * 1024

# Delta-stream states retained per client: each holds a full payload
# snapshot, so a caller cycling stream names (against the keep-it-
# constant guidance) must evict instead of growing without bound.
# Mirrors the server's _MAX_DELTA_BASES.
_MAX_DELTA_STREAMS = 32

# Rails a striped payload fans out over — bounded so a generous
# connections_per_peer doesn't shred one payload into dozens of tiny
# interleaved flows (past ~4 rails a single sender saturates either the
# NIC or the CRC/copy stage anyway).
MAX_STRIPE_RAILS = 4

# Shared-memory sends at/under this size materialize INLINE on the
# event loop: the copy is a few µs, while an executor round trip costs
# two thread wakeups + GIL handoffs — pure overhead at stripe scale.
# Above it, the gather (and any device→host produce) moves off-loop so
# a large handoff can't stall unrelated traffic sharing the loop.
_INLINE_MATERIALIZE_BYTES = 256 * 1024


def _default_stripe_rails() -> int:
    """Host-adaptive rail count: striping pays only when spare cores
    run the per-rail CRC/copy stages concurrently with the socket
    writes.  On a 1-2 core host every rail shares one core AND the
    receiver pays an extra reassembly memcpy per byte — measured 2×
    SLOWER than the single-frame path there — so few-core hosts keep
    one rail (striping off) and the wire-v3 single-frame pipeline.
    The ``stripe_rails`` transport option overrides this (tests and
    the multirail bench force it)."""
    import os

    return max(1, min(MAX_STRIPE_RAILS, (os.cpu_count() or 2) // 2))


class SendError(ConnectionError):
    pass


class FatalSendError(SendError):
    """A send rejected by the peer for a non-transient reason — not retried."""


class ProtocolMismatchError(FatalSendError):
    """The peer speaks a different wire-protocol version.

    Raised from the connection HELLO handshake (wire v4) — naming both
    versions — instead of letting a mixed-version pair fail later with
    a confusing manifest-decode error mid-payload."""


class DeltaBaseError(SendError):
    """The receiver's delta base is missing/desynced (e.g. it restarted).

    Not a transport failure: the stream send path catches it and
    immediately re-sends the full payload, re-seeding both caches."""


class _SendArena:
    """Reusable page-aligned send buffer (anonymous mmap).

    mmap gives page alignment and lazily-faulted memory — the closest
    portable stand-in for a pinned DMA arena — and reuse across rounds
    keeps the pages hot instead of paying a fresh multi-MB allocation
    (plus its page-fault storm) per round, which is exactly the
    alloc+concat+copy the old snapshot path did."""

    __slots__ = ("mm", "size")

    def __init__(self, size: int) -> None:
        import mmap

        self.size = max(1, int(size))
        self.mm = mmap.mmap(-1, self.size)

    def view(self, size: int) -> memoryview:
        return memoryview(self.mm)[:size]


class _DeltaStream:
    """Last-ACKED payload snapshot for one (dest, stream) delta cache."""

    __slots__ = ("data", "ccrc", "fp", "lock", "arenas")

    def __init__(self) -> None:
        self.data: Optional[bytes] = None  # full payload the peer holds
        self.ccrc: Optional[List[int]] = None
        self.fp: int = 0
        # Serializes stream sends end-to-end (through the ACK): a delta
        # only makes sense against the receiver's CURRENT base, and two
        # in-flight sends on different pooled connections could arrive
        # reordered.
        self.lock = asyncio.Lock()
        # Two ping-pong send arenas: the next snapshot is written into
        # whichever slot the current base (self.data) does NOT alias, so
        # the base bytes stay stable for delta diffing and for the
        # receiver's retry semantics.  A failed send leaves the base
        # slot untouched and the next attempt reuses the other slot.
        self.arenas: List[Optional[_SendArena]] = [None, None]

    def writable_arena(self, size: int) -> memoryview:
        """A view over the arena slot not backing the current base."""
        base_obj = self.data.obj if isinstance(self.data, memoryview) else None
        for i, arena in enumerate(self.arenas):
            if arena is not None and arena.mm is base_obj:
                continue
            if arena is None or arena.size < size or arena.size > 2 * max(size, 1):
                arena = _SendArena(size)
                self.arenas[i] = arena
            return arena.view(size)
        # Unreachable (the base aliases at most one slot) — keep a safe
        # fallback rather than an assert on a hot path.
        arena = _SendArena(size)
        self.arenas[0] = arena
        return arena.view(size)


def _iter_chunk_views(payload_bufs: List, csz: int, timings: Dict[str, float]):
    """Yield ``(nbytes, [views])`` covering the payload in ``csz`` chunks.

    Buffers materialize lazily in walk order — a LazyBuffer's
    device→host fetch happens when the walk first reaches it, i.e.
    while earlier chunks are already on a socket — and a chunk spanning
    buffer boundaries yields multiple views (vectored write, no copy).
    ``timings["d2h"]`` accumulates the fetch seconds.
    """
    cur: List = []
    cur_n = 0
    for buf in payload_bufs:
        t0 = time.perf_counter()
        host = buf.produce() if isinstance(buf, wire.LazyBuffer) else buf
        mv = host if isinstance(host, memoryview) else memoryview(host)
        if mv.format != "B":
            mv = mv.cast("B")
        timings["d2h"] += time.perf_counter() - t0
        off = 0
        while off < mv.nbytes:
            take = min(csz - cur_n, mv.nbytes - off)
            cur.append(mv[off : off + take])
            cur_n += take
            off += take
            if cur_n == csz:
                yield cur_n, cur
                cur, cur_n = [], 0
    if cur_n:
        yield cur_n, cur


def _resolve_ready(fut, item) -> None:
    if not fut.done():
        fut.set_result(item)


def _fail_ready(fut, exc) -> None:
    if not fut.done():
        fut.set_exception(exc)


class _Conn:
    """One pooled connection: socket, reader task, in-flight futures."""

    __slots__ = (
        "reader", "writer", "reader_task", "pending", "write_lock", "fd", "dead"
    )

    def __init__(self, reader, writer, fd: Optional[int]) -> None:
        self.reader = reader
        self.writer = writer
        self.fd = fd  # raw-writev path; None on TLS (SSL owns the socket)
        self.reader_task: Optional[asyncio.Task] = None
        self.pending: Dict[int, asyncio.Future] = {}
        self.write_lock = asyncio.Lock()
        self.dead = False  # teardown requested; close deferred past writes

    @property
    def busy(self) -> int:
        return len(self.pending) + (1 if self.write_lock.locked() else 0)

    @property
    def closed(self) -> bool:
        return self.dead or self.writer is None or self.writer.is_closing()


class TransportClient:
    def __init__(
        self,
        src_party: str,
        dest_party: str,
        address: str,
        retry_policy: RetryPolicy,
        timeout_s: float,
        max_message_size: int,
        metadata: Optional[Dict[str, str]] = None,
        ssl_context: Optional[ssl.SSLContext] = None,
        server_hostname: Optional[str] = None,
        checksum: Optional[bool] = None,
        pool_size: int = 2,
        loop: Optional[asyncio.AbstractEventLoop] = None,
        stripe_rails: Optional[int] = None,
        dead_check: Optional[Any] = None,
        secagg: Optional[Any] = None,
        local_link: str = "off",
        checksum_pinned: bool = False,
    ) -> None:
        if checksum is None:
            # Match the manager's policy: checksum only when the fast C++
            # CRC path is built.  A directly-constructed client otherwise
            # pays a ~MB/s pure-Python CRC on the event loop for a digest
            # that a native-less receiver skips verifying anyway.
            from rayfed_tpu import native

            checksum = native.is_available()
        self._checksum = checksum
        # Local-link fast path (transport/local.py).  The backend is a
        # PER-LINK decision made once, on the first contact with the
        # destination: same process → shared-memory handoff; same host
        # (HELLO colocation proof) → the peer's AF_UNIX twin listener;
        # otherwise (or on any local-path failure) TCP, loudly.  CRC is
        # elided on adopted local links — the bytes never cross a wire —
        # unless the operator pinned `checksum` explicitly.  A TLS link
        # never upgrades: the operator asked for encryption, and an
        # AF_UNIX socket silently dropping it is not a fast path.
        local_link = str(local_link or "off").lower()
        if local_link not in local.LINK_MODES:
            logger.warning(
                "[%s] unknown local_link mode %r for %s; using 'off'",
                src_party, local_link, dest_party,
            )
            local_link = "off"
        if local_link != "off" and ssl_context is not None:
            logger.warning(
                "[%s] local_link=%r to %s disabled: the link is TLS and "
                "must not downgrade to a plaintext local socket",
                src_party, local_link, dest_party,
            )
            local_link = "off"
        self._local_mode = local_link
        self._local_decided = local_link == "off"
        self._link_backend = "tcp"  # tcp | uds | shm (live backend)
        self._local_endpoint: Optional[local.LocalEndpoint] = None
        self._uds_path: Optional[str] = None
        self._local_fallback: Optional[str] = None  # decision/fallback reason
        self._checksum_cfg = checksum  # restore on TCP fallback
        self._checksum_pinned = bool(checksum_pinned)
        self._src_party = src_party
        self._dest_party = dest_party
        host, _, port = address.rpartition(":")
        self._host = host
        self._port = int(port)
        self._retry_policy = retry_policy
        # Known-dead fast-fail: () -> bool, True while the destination
        # is declared dead by the health monitor (the manager wires the
        # mailbox's dead-party snapshot in).  A send still makes ONE
        # attempt — the snapshot lags recovery by up to a ping cycle —
        # but the backoff ladder is skipped: retrying a corpse burns the
        # full ladder (measured 65 s on poison pushes) for nothing, and
        # the monitor's pings, not sends, are what detect revival.
        self._dead_check = dead_check
        self._timeout_s = timeout_s
        self._max_message_size = max_message_size
        self._metadata = dict(metadata or {})
        self._ssl_context = ssl_context
        self._server_hostname = server_hostname
        # Event loop the coroutines run on, when known (the manager
        # passes its loop thread's).  Only send_data_async needs it —
        # the coroutine API is loop-agnostic as ever.
        self._loop = loop
        self._rid = itertools.count(1)
        # Stripe-payload generation ids (wire v4): monotonically
        # increasing, so the receiver can tell a retry's fresh stripe
        # group from a stale frame of the failed attempt.
        self._sid = itertools.count(1)
        # Version advertised in the connection HELLO handshake —
        # overridable so tests can exercise the mismatch path.
        self._proto_version = wire.WIRE_FORMAT_VERSION
        # Secure-aggregation key agreement (transport/secagg.py): when
        # set, every HELLO this client opens publishes the local key
        # advertisement and records the server's from the reply — one
        # connection establishes the pair's mask-seed state both ways.
        self._secagg = secagg
        self._conns: List[_Conn] = []
        self._conn_lock = asyncio.Lock()
        self._pool_size = max(1, int(pool_size))
        # Rails a striped payload fans over: explicit option, else the
        # host-adaptive default (1 on few-core hosts = striping off).
        self._stripe_rails_opt = (
            None if stripe_rails is None else max(1, int(stripe_rails))
        )
        # Dedicated control connection for health pings: a data
        # connection's write lock is held for a whole frame, so a ping
        # queued on the pool behind a multi-GB push would time out and
        # the health monitor would declare a busy-but-healthy peer
        # dead.  Opened lazily on the first ctl ping only — one-shot
        # readiness pings ride (and warm) the data pool instead.
        self._ctl_conn: Optional[_Conn] = None
        self._ctl_lock = asyncio.Lock()
        self._closed = False
        # Whole-operation in-flight send count (loop thread only):
        # incremented for the FULL span of every send_data call —
        # including producer fetches before the first frame, retry
        # backoffs, and connection opens, none of which show up in
        # per-connection pending/lock state.  The message-cap mutation
        # guard reads it so a cap change can't slip into one of those
        # windows and torn-apply to a payload legal when initiated.
        self._inflight_sends = 0
        # Per-(dest, stream) delta caches — the last payload the peer
        # ACKed on each stream, diffed against the next send so only
        # changed DELTA_CHUNK_BYTES ranges (+ a bitmap manifest) ship.
        # Bounded LRU (one full payload snapshot per entry); accessed on
        # the loop thread only.
        self._delta_streams: "collections.OrderedDict[str, _DeltaStream]" = (
            collections.OrderedDict()
        )
        # Send-pipeline accounting (loop-thread only): wall time of
        # payload frames vs the executor time spent preparing bytes
        # (device→host fetch + checksum) and writing them.  prepare +
        # write > wall means the chunk pipeline overlapped them.
        self.stats: Dict[str, Any] = {
            "send_frames": 0,
            "send_payload_bytes": 0,
            "send_prepare_s": 0.0,
            "send_write_s": 0.0,
            "send_frame_wall_s": 0.0,
            # Delta-cache accounting: logical payload bytes represented
            # by stream sends vs bytes actually shipped (changed chunks
            # + full re-seeds).  1 - wire/logical = the saved fraction.
            "delta_stream_frames": 0,
            "delta_full_frames": 0,
            "delta_logical_bytes": 0,
            "delta_wire_bytes": 0,
            # Send-path stage breakdown (the gap-can't-silently-reopen
            # telemetry): device→host fetch, arena/gather copy, CRC,
            # ready→write loop handoff wait, and raw socket time.
            "send_d2h_s": 0.0,
            "send_copy_s": 0.0,
            "send_crc_s": 0.0,
            "send_loop_wait_s": 0.0,
            "send_socket_s": 0.0,
            # Multi-rail striping accounting.
            "send_striped_payloads": 0,
            "send_stripe_frames": 0,
        }
        # Per-backend split of the stage breakdown (tcp/uds/shm): the
        # suffixed counters sum to the unsuffixed ones above, so a
        # local-link regression is attributable from metrics alone.
        # For shm, "socket" is the handoff→ACK wait (there is no
        # socket; the receiver's dispatch latency plays its role).
        for _b in ("tcp", "uds", "shm"):
            for _k in ("d2h", "copy", "crc", "loop_wait", "socket"):
                self.stats[f"send_{_k}_s_{_b}"] = 0.0

    def _bill_backend(
        self, backend: Optional[str] = None, d2h: float = 0.0,
        copy: float = 0.0, crc: float = 0.0, loop_wait: float = 0.0,
        socket: float = 0.0,
    ) -> None:
        """Accumulate stage seconds under the live backend's counters
        (the unsuffixed totals are billed by the callers as before)."""
        b = backend or self._link_backend
        st = self.stats
        if d2h:
            st[f"send_d2h_s_{b}"] += d2h
        if copy:
            st[f"send_copy_s_{b}"] += copy
        if crc:
            st[f"send_crc_s_{b}"] += crc
        if loop_wait:
            st[f"send_loop_wait_s_{b}"] += loop_wait
        if socket:
            st[f"send_socket_s_{b}"] += socket

    def local_link_info(self) -> Dict[str, Any]:
        """The link's backend decision, for effective_transport_options:
        configured mode, the live backend, whether the decision is made
        (first contact decides), and the fallback/decision reason."""
        return {
            "mode": self._local_mode,
            "backend": self._link_backend,
            "decided": self._local_decided,
            "fallback": self._local_fallback,
        }

    # -- local-link backend decision ------------------------------------------

    def _adopt_local(self, backend: str) -> None:
        self._local_decided = True
        self._link_backend = backend
        if not self._checksum_pinned:
            # CRC elision on trusted local links: the bytes never leave
            # the machine, so the whole-payload CRC32C guards nothing a
            # kernel memcpy doesn't already.  (Per-chunk stream CRCs
            # survive on uds — they double as the delta cache's base
            # fingerprints; shm bypasses the delta machinery entirely.)
            self._checksum = False
        logger.debug(
            "[%s] link to %s upgraded to %s",
            self._src_party, self._dest_party, backend,
        )

    def _adopt_shm(self, endpoint: local.LocalEndpoint) -> None:
        self._local_endpoint = endpoint
        self._adopt_local("shm")

    def _pin_tcp(self, reason: str, loud: bool = False) -> None:
        """Decide (or fall back to) TCP for this link.  ``loud`` marks a
        degradation the operator asked not to have (forced uds/shm that
        can't hold, a mid-session AF_UNIX failure) vs auto-detection
        correctly concluding the peer is remote."""
        self._local_decided = True
        self._link_backend = "tcp"
        self._local_endpoint = None
        self._uds_path = None
        self._local_fallback = reason
        self._checksum = self._checksum_cfg
        (logger.warning if loud else logger.debug)(
            "[%s] local link to %s: using TCP — %s",
            self._src_party, self._dest_party, reason,
        )

    def _consider_upgrade(self, reply: Dict[str, Any]) -> Optional[str]:
        """Decide the link backend from a HELLO reply's advertisement.

        Returns "uds" when the caller must redial over the advertised
        AF_UNIX path; "shm"/None mean the connection at hand stays
        usable (shm routes DATA through the in-process handoff but keeps
        the TCP connection as a valid control path)."""
        self._local_decided = True
        mode = self._local_mode
        if mode in ("auto", "shm"):
            ep = local.lookup_token(reply.get(wire.LOCAL_TOKEN_KEY))
            if ep is not None:
                self._adopt_shm(ep)
                return "shm"
            if mode == "shm":
                self._pin_tcp(
                    "local_link=shm but the destination server does not "
                    "live in this process", loud=True,
                )
                return None
        host_id = reply.get(wire.LOCAL_HOST_KEY)
        uds_path = reply.get(wire.LOCAL_UDS_KEY)
        colocated = (
            host_id is not None and host_id == local.host_identity()
        )
        if mode == "uds" or (mode == "auto" and colocated):
            if uds_path:
                if not colocated:
                    # Forced uds without the boot-scoped host proof:
                    # honor the operator (containers can hide
                    # machine-id while sharing a mount), but say so.
                    logger.warning(
                        "[%s] local_link=uds to %s: no colocation proof "
                        "(host identity mismatch); trusting the "
                        "advertised path %s",
                        self._src_party, self._dest_party, uds_path,
                    )
                self._uds_path = uds_path
                self._adopt_local("uds")
                return "uds"
            self._pin_tcp(
                "peer advertises no AF_UNIX listener",
                loud=(mode == "uds"),
            )
            return None
        self._pin_tcp(
            "peer is not colocated" if not colocated
            else f"local_link={mode!r} declines this backend",
        )
        return None

    async def _ensure_local_backend(self) -> None:
        """Make the link's backend decision before the first operation.

        Same-process destinations are found in the local registry with
        NO socket at all (at N=64 virtual parties, probe connections
        alone were a ~2k-socket storm per round); otherwise one pooled
        TCP connection's HELLO reply carries the advertisement and
        :meth:`_open_conn` applies the upgrade."""
        if self._local_decided:
            return
        if self._local_mode in ("auto", "shm"):
            ep = local.lookup_addr(self._host, self._port)
            if ep is not None:
                self._adopt_shm(ep)
                return
        try:
            await self._acquire_conn()
        except asyncio.CancelledError:
            raise
        except Exception:
            # The probe failed before any HELLO decided anything: leave
            # the decision open — the operation's own connect path
            # surfaces (and retries) the real error.
            pass

    # -- connection management ------------------------------------------------

    async def _open_conn(self) -> _Conn:
        if chaos.installed() is not None:
            await chaos.fire_async(
                "connect", party=self._src_party, dest=self._dest_party
            )
        use_uds = self._link_backend == "uds" and self._uds_path is not None
        if use_uds:
            try:
                reader, writer = await asyncio.open_unix_connection(
                    self._uds_path, limit=2**20
                )
            except (OSError, NotImplementedError) as e:
                # Loud mid-session fallback: the peer restarted (socket
                # unlinked) or the path went away.  TCP (and the
                # configured checksum policy) is restored for good.
                self._pin_tcp(
                    f"AF_UNIX connect to {self._uds_path} failed: {e}",
                    loud=True,
                )
                use_uds = False
        if not use_uds:
            reader, writer = await asyncio.open_connection(
                self._host,
                self._port,
                ssl=self._ssl_context,
                server_hostname=(
                    self._server_hostname if self._ssl_context else None
                ),
                limit=2**20,
            )
        fd: Optional[int] = None
        if self._ssl_context is None:
            from rayfed_tpu import native

            if native.is_available():
                sock = writer.get_extra_info("socket")
                if sock is not None:
                    fd = sock.fileno()
        conn = _Conn(reader, writer, fd)
        conn.reader_task = asyncio.ensure_future(self._read_responses(conn))
        # Version handshake (wire v4): one HELLO round trip before the
        # connection carries data.  A mixed-version pair fails HERE with
        # ProtocolMismatchError naming both versions, instead of a
        # confusing manifest-decode error mid-payload.
        try:
            hello = {"src": self._src_party, "ver": self._proto_version}
            if self._secagg is not None:
                hello[wire.SECAGG_PUB_KEY] = self._secagg.hello_value()
            reply = await self._roundtrip(
                wire.MSG_HELLO,
                hello,
                [],
                timeout_s=min(self._timeout_s, 15.0),
                conn=conn,
            )
            if self._secagg is not None:
                peer_adv = reply.get(wire.SECAGG_PUB_KEY)
                if peer_adv:
                    self._secagg.record_peer(self._dest_party, peer_adv)
        except BaseException:
            if conn.reader_task is not None:
                conn.reader_task.cancel()
                conn.reader_task = None
            self._teardown(conn, SendError("handshake failed"))
            raise
        if not self._local_decided:
            # First contact decides the link backend from the HELLO
            # advertisement (transport/local.py).  A uds verdict retires
            # this TCP probe and redials over the advertised path —
            # depth-1 recursion, the decision is made now.
            if self._consider_upgrade(reply) == "uds":
                if conn.reader_task is not None:
                    conn.reader_task.cancel()
                    conn.reader_task = None
                self._teardown(
                    conn, SendError("link upgraded to AF_UNIX")
                )
                return await self._open_conn()
        return conn

    async def _acquire_rails(self, k: int) -> List[_Conn]:
        """``k`` distinct live connections for a striped send (grow the
        pool as needed; least-busy first)."""
        async with self._conn_lock:
            self._conns = [c for c in self._conns if not c.closed]
            while len(self._conns) < k:
                self._conns.append(await self._open_conn())
            return sorted(self._conns, key=lambda c: c.busy)[:k]

    def _stripe_rails(self) -> int:
        rails = (
            self._stripe_rails_opt
            if self._stripe_rails_opt is not None
            else _default_stripe_rails()
        )
        return max(1, min(self._pool_size, MAX_STRIPE_RAILS, rails))

    async def _acquire_conn(self) -> _Conn:
        """Pick the least-busy live connection; grow the pool under load."""
        self._conns = [c for c in self._conns if not c.closed]
        if self._conns:
            conn = min(self._conns, key=lambda c: c.busy)
            if conn.busy == 0 or len(self._conns) >= self._pool_size:
                return conn
        async with self._conn_lock:
            self._conns = [c for c in self._conns if not c.closed]
            idle = [c for c in self._conns if c.busy == 0]
            if idle:
                return idle[0]
            if len(self._conns) < self._pool_size or not self._conns:
                conn = await self._open_conn()
                self._conns.append(conn)
                return conn
            return min(self._conns, key=lambda c: c.busy)

    async def _acquire_ctl_conn(self) -> _Conn:
        async with self._ctl_lock:
            if self._closed:
                # A ping racing close() must not resurrect a connection
                # (and its reader task) that close() will never see.
                raise SendError(f"client to {self._dest_party} closed")
            if self._ctl_conn is None or self._ctl_conn.closed:
                self._ctl_conn = await self._open_conn()
            return self._ctl_conn

    async def _read_responses(self, conn: _Conn) -> None:
        # Local snapshot: _teardown/_really_close null conn.reader, and
        # a cancel() issued between this task's awaits is only DELIVERED
        # at the next await — the attribute read before it must not race
        # the close into an AttributeError (the stream object itself
        # just raises IncompleteReadError once its transport closed).
        reader = conn.reader
        try:
            while True:
                prefix = await reader.readexactly(wire.HEADER_SIZE)
                msg_type, _flags, hlen, plen = wire.unpack_frame_prefix(prefix)
                header = json.loads(await reader.readexactly(hlen)) if hlen else {}
                if plen:
                    await reader.readexactly(plen)
                rid = header.get("rid")
                fut = conn.pending.pop(rid, None)
                if fut is None or fut.done():
                    continue
                if msg_type == wire.MSG_ERR:
                    if header.get("code") == "protocol":
                        exc_cls = ProtocolMismatchError
                    elif header.get("fatal"):
                        exc_cls = FatalSendError
                    elif header.get("code") == "delta_base":
                        exc_cls = DeltaBaseError
                    else:
                        exc_cls = SendError
                    fut.set_exception(exc_cls(header.get("error", "remote error")))
                else:
                    fut.set_result(header)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError) as e:
            self._teardown(conn, SendError(f"connection to {self._dest_party} lost: {e}"))
        except asyncio.CancelledError:
            self._teardown(conn, SendError("client shutting down"))
            raise

    def _teardown(self, conn: _Conn, exc: Exception) -> None:
        """Retire one connection and fail its in-flight requests.

        The actual socket close is deferred while a write holds the lock:
        closing mid-``writev`` would free the fd under an executor thread,
        and a recycled fd number could splice this payload into an
        unrelated connection.  The write path closes on exit when it sees
        ``dead``.
        """
        conn.dead = True
        pending, conn.pending = conn.pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(exc)
        if conn in self._conns:
            self._conns.remove(conn)
        if not conn.write_lock.locked():
            self._really_close(conn)

    def _really_close(self, conn: _Conn) -> None:
        if conn.writer is not None:
            try:
                conn.writer.close()
            except Exception:
                pass
        conn.writer = None
        conn.reader = None
        conn.fd = None

    async def close(self) -> None:
        # Under _ctl_lock: a concurrent ping past the _closed check in
        # _acquire_ctl_conn may be mid-_open_conn — waiting for the lock
        # here means either we see its fresh connection (and drain it
        # below) or it sees _closed and never opens one.  Setting _closed
        # without the lock leaked exactly that socket + reader task.
        async with self._ctl_lock:
            self._closed = True
            if self._ctl_conn is not None:
                self._conns.append(self._ctl_conn)  # close with the rest
                self._ctl_conn = None
        for conn in list(self._conns):
            if conn.reader_task is not None:
                conn.reader_task.cancel()
                try:
                    await conn.reader_task
                except (asyncio.CancelledError, Exception):
                    pass
                conn.reader_task = None
            self._teardown(conn, SendError("client closed"))
        self._conns = []

    # -- RPCs -----------------------------------------------------------------

    async def _roundtrip(
        self, msg_type: int, header: Dict[str, Any], payload_bufs: List,
        crc_trailer: bool = False, timeout_s: Optional[float] = None,
        conn: Optional[_Conn] = None,
    ) -> Dict[str, Any]:
        if chaos.installed() is not None:
            # Chaos "wire" hook: fires on EVERY outbound frame — data,
            # health pings, handshakes — so a partition rule makes the
            # destination look exactly dead to this endpoint (the "frame"
            # hook below covers data frames only).  Raised faults are
            # ConnectionErrors: pings report False, sends hit the retry
            # arms, before any connection state is touched.
            await chaos.fire_async(
                "wire", party=self._src_party, dest=self._dest_party,
                type=msg_type,
            )
        if conn is None:
            conn = await self._acquire_conn()
        rid = next(self._rid)
        header = dict(header, rid=rid)
        if msg_type == wire.MSG_DATA and chaos.installed() is not None:
            # Chaos "frame" hook: may delay this frame, drop it (raises
            # a retryable ChaosFault), kill the rail, or corrupt the
            # DECLARED checksum in the (mutable) header so the
            # receiver's verification + the sender's retry path run.
            await chaos.fire_async(
                "frame", party=self._src_party, dest=self._dest_party,
                header=header,
            )
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        conn.pending[rid] = fut
        payload_len = wire.payload_nbytes(payload_bufs)
        flags = wire.FLAG_CRC_TRAILER if crc_trailer else 0
        try:
            async with conn.write_lock:
                try:
                    if conn.closed:
                        raise SendError(
                            f"connection to {self._dest_party} closed"
                        )
                    frame_bufs = wire.pack_frame(
                        msg_type, header, payload_len=payload_len, flags=flags
                    )
                    await self._write_frame(
                        loop, conn, frame_bufs, payload_bufs, crc_trailer
                    )
                except (SendError, ConnectionError, OSError,
                        asyncio.IncompleteReadError):
                    raise  # classified by the outer arms
                except BaseException as e:
                    # Any other failure mid-write (a device→host fetch
                    # raising inside LazyBuffer.produce, cancellation)
                    # leaves the stream desynced: the frame prefix
                    # already declared payload_len, so the NEXT frame's
                    # bytes would be consumed as this one's payload.
                    # The connection is unrecoverable — tear it down.
                    # (Scoped to the write: cancellation while awaiting
                    # the ACK below leaves a healthy stream.)
                    self._teardown(
                        conn,
                        SendError(
                            f"payload write to {self._dest_party} failed: {e}"
                        ),
                    )
                    raise
                finally:
                    if conn.dead:
                        self._really_close(conn)
            return await asyncio.wait_for(
                fut, timeout=self._timeout_s if timeout_s is None else timeout_s
            )
        except asyncio.TimeoutError:
            # Deadline on the ACK.  Must precede the connection-failure
            # arm: since 3.10 TimeoutError IS an OSError subclass, and a
            # deadline must not tear down a healthy pooled connection
            # (or get retried — the policy says deadlines aren't).
            raise
        except SendError:
            # App-level MSG_ERR reply for THIS request (e.g. checksum
            # mismatch, oversize).  The connection itself is healthy —
            # don't tear it down or fail the other pipelined sends.
            # (SendError subclasses ConnectionError, so this arm must
            # precede the connection-failure arm.)
            raise
        except (ConnectionError, OSError, asyncio.IncompleteReadError) as e:
            self._teardown(conn, SendError(str(e)))
            raise SendError(str(e)) from e
        finally:
            conn.pending.pop(rid, None)
            # A write failure raises out of this coroutine after
            # _teardown already set an exception on our own ACK future;
            # mark it retrieved so GC doesn't log "Future exception was
            # never retrieved" (the caller sees the write error instead).
            if fut.done() and not fut.cancelled():
                fut.exception()

    async def _write_frame(
        self, loop, conn: _Conn, frame_bufs: List, payload_bufs: List,
        crc_trailer: bool,
    ) -> None:
        """Write one frame (prefix+header+payload[+crc trailer]).

        Native path (non-TLS, C++ built): bytes go straight to the kernel
        via ``writev`` in an executor thread — the event loop never
        copies or blocks.  The payload is cut into
        :data:`WRITE_CHUNK_BYTES` chunks and fully pipelined: the
        device→host fetch of lazy shard k+1 AND the checksum of chunk
        k+1 run in executor threads while chunk k's writev blocks in
        another, so a large payload's encode/compress cost hides under
        the wire instead of serializing in front of it.  Fallback:
        asyncio writer (same pipeline, SSL owns the socket).
        """
        if crc_trailer:
            from rayfed_tpu import native

        use_fd = conn.fd is not None
        if use_fd:
            from rayfed_tpu import native as _native

            timeout_ms = max(1000, int(self._timeout_s * 1000))
            fd = conn.fd  # capture: teardown may null it under our feet

            def _writev(bufs):
                try:
                    _native.writev_full(fd, bufs, timeout_ms=timeout_ms)
                except TimeoutError as e:
                    # A stalled fd mid-frame desyncs the stream; surface
                    # as a connection failure (teardown), NOT a deadline
                    # (OSError(ETIMEDOUT) auto-subclasses TimeoutError,
                    # which the roundtrip treats as a healthy-conn ACK
                    # deadline).
                    raise ConnectionResetError(
                        f"write to {self._dest_party} stalled: {e}"
                    ) from e

        write_s = 0.0

        async def _write(bufs: List) -> None:
            nonlocal write_s
            t0 = time.perf_counter()
            if use_fd:
                await loop.run_in_executor(None, _writev, bufs)
            else:
                for buf in bufs:
                    conn.writer.write(buf)
                await conn.writer.drain()
            write_s += time.perf_counter() - t0

        if not payload_bufs:
            await _write(frame_bufs)
            return

        def _produce(buf):
            """Executor hop: materialize one payload buffer as a byte view."""
            t0 = time.perf_counter()
            host = buf.produce() if isinstance(buf, wire.LazyBuffer) else buf
            mv = host if isinstance(host, memoryview) else memoryview(host)
            if mv.format != "B":
                mv = mv.cast("B")
            return mv, time.perf_counter() - t0

        def _crc(view, seed):
            t0 = time.perf_counter()
            # Chained seed: the trailer equals crc32c(concat(payload)).
            return native.crc32c(view, seed), time.perf_counter() - t0

        t_frame0 = time.perf_counter()
        prepare_s = 0.0
        d2h_s = 0.0
        crc_s = 0.0
        payload_nbytes = 0
        crc = 0
        head: List = list(frame_bufs)  # rides along with the first chunk
        prefetch = loop.run_in_executor(None, _produce, payload_bufs[0])
        for i in range(len(payload_bufs)):
            mv, dt = await prefetch
            prepare_s += dt
            d2h_s += dt
            payload_nbytes += mv.nbytes
            if i + 1 < len(payload_bufs):
                prefetch = loop.run_in_executor(
                    None, _produce, payload_bufs[i + 1]
                )
            nchunks = max(1, -(-mv.nbytes // WRITE_CHUNK_BYTES))
            views = [
                mv[j * WRITE_CHUNK_BYTES : (j + 1) * WRITE_CHUNK_BYTES]
                for j in range(nchunks)
            ]
            crc_fut = (
                loop.run_in_executor(None, _crc, views[0], crc)
                if crc_trailer
                else None
            )
            last_buf = i == len(payload_bufs) - 1
            for j, view in enumerate(views):
                if crc_trailer:
                    crc, dt = await crc_fut
                    prepare_s += dt
                    crc_s += dt
                    if j + 1 < len(views):
                        crc_fut = loop.run_in_executor(
                            None, _crc, views[j + 1], crc
                        )
                chunk = head + [view]
                head = []
                if last_buf and j == len(views) - 1 and crc_trailer:
                    chunk.append(struct.pack(">I", crc))
                await _write(chunk)
        self.stats["send_frames"] += 1
        self.stats["send_payload_bytes"] += payload_nbytes
        self.stats["send_prepare_s"] += prepare_s
        self.stats["send_write_s"] += write_s
        self.stats["send_d2h_s"] += d2h_s
        self.stats["send_crc_s"] += crc_s
        self.stats["send_socket_s"] += write_s
        self._bill_backend(d2h=d2h_s, crc=crc_s, socket=write_s)
        frame_wall = time.perf_counter() - t_frame0
        self.stats["send_frame_wall_s"] += frame_wall
        _tr = telemetry.active()
        if _tr is not None:
            # The PR 5 send-path stage breakdown as a SPAN: one record
            # per payload frame with where its wall actually went
            # (device→host fetch, checksum, socket) — what get_stats'
            # cumulative {encode,d2h,crc,loop_wait,socket}_ms can only
            # show summed over the whole session.  Ring append only —
            # this coroutine runs on the transport loop.
            _tr.emit(
                "wire.frame", party=self._src_party,
                peer=self._dest_party, nbytes=payload_nbytes,
                t_start=time.time() - frame_wall, dur_s=frame_wall,
                detail={
                    "d2h_ms": round(d2h_s * 1e3, 3),
                    "crc_ms": round(crc_s * 1e3, 3),
                    "socket_ms": round(write_s * 1e3, 3),
                },
            )

    def _dest_known_dead(self) -> bool:
        """True while the health monitor has the destination declared
        dead — the retry ladders consult this and stop immediately
        instead of sleeping out the backoff sequence."""
        if self._dead_check is None:
            return False
        try:
            return bool(self._dead_check())
        except Exception:  # pragma: no cover - monitor accessor bug
            return False

    def _dead_fast_fail(self, last_exc: Optional[Exception]) -> None:
        raise SendError(
            f"destination {self._dest_party!r} is declared dead by the "
            f"health monitor; skipping the retry backoff ladder "
            f"(last attempt: {last_exc})"
        ) from last_exc

    @property
    def checksum_enabled(self) -> bool:
        return self._checksum

    def has_inflight_sends(self) -> bool:
        """True while any :meth:`send_data` call is in progress — from
        entry (producer fetches, connection opens, retry backoffs)
        through the final ACK — or any pooled connection has an
        un-ACKed frame / held write lock (direct ``_roundtrip``
        callers): the runtime message-size mutation guard (a cap change
        must reject cleanly rather than torn-apply to a payload on the
        wire)."""
        if self._inflight_sends > 0:
            return True
        for conn in self._conns:
            if conn.pending or conn.write_lock.locked():
                return True
        return any(st.lock.locked() for st in self._delta_streams.values())

    # -- multi-rail striped sends (wire v4) -----------------------------------

    def _produce_plain_chunks(
        self, loop, payload_bufs, csz, ready, abort=None
    ) -> None:
        """Executor job: cut the payload into ``csz`` chunks as
        zero-copy views (lazy buffers fetched in walk order) + per-chunk
        CRC, resolving ``ready[i]`` as chunk ``i`` becomes shippable —
        chunk k is written to a rail while chunk k+1 is still being
        fetched from device and CRC'd here.  ``abort`` (threading.Event)
        stops production between chunks: a failed attempt must not make
        its retry wait out the full d2h+CRC pass of a dead payload."""
        import zlib

        timings = {"d2h": 0.0}
        idx = 0
        d2h_prev = 0.0
        try:
            for _nbytes, views in _iter_chunk_views(payload_bufs, csz, timings):
                if abort is not None and abort.is_set():
                    raise SendError("send aborted; chunk production stopped")
                t0 = time.perf_counter()
                crc = 0
                for v in views:
                    crc = zlib.crc32(v, crc)
                crc_s = time.perf_counter() - t0
                d2h_s = timings["d2h"] - d2h_prev
                d2h_prev = timings["d2h"]
                item = (
                    idx, crc, list(views), time.perf_counter(),
                    d2h_s, 0.0, crc_s,
                )
                loop.call_soon_threadsafe(_resolve_ready, ready[idx], item)
                idx += 1
        # fedlint: disable=FED004 — transferred, not swallowed: the failure fails every pending rail future; this runs on the codec pool, not the driver
        except BaseException as e:  # fail the rails, not the executor
            for fut in ready[idx:]:
                loop.call_soon_threadsafe(_fail_ready, fut, e)

    def _produce_arena_chunks(
        self, loop, payload_bufs, arena_mv, csz,
        base_mv=None, base_ccrc=None, ready=None, abort=None,
    ):
        """Executor job: ONE pass copying the payload into the send
        arena chunk-by-chunk, CRC'ing each chunk as it lands and — when
        a delta base is supplied — computing its changed flag in the
        same pass (the diff aliases both arenas; no re-copy).  With
        ``ready``, ``ready[i]`` resolves as chunk ``i`` lands, so the
        fresh-payload striped path ships chunk k while chunk k+1 is
        still being fetched/copied/CRC'd.

        Returns ``(ccrcs, changed, (d2h_s, copy_s, crc_s))`` —
        ``changed`` is None without a base; the totals are billed by
        the caller on the loop thread (the pipelined path bills per
        chunk through the ready items instead).
        """
        import zlib

        import numpy as np

        ccrcs: List[int] = []
        changed: Optional[List[int]] = [] if base_mv is not None else None
        timings = {"d2h": 0.0}
        d2h_prev = copy_total = crc_total = 0.0
        idx = 0
        chunk_start = 0
        try:
            for nbytes, views in _iter_chunk_views(payload_bufs, csz, timings):
                if abort is not None and abort.is_set():
                    raise SendError("send aborted; chunk production stopped")
                t0 = time.perf_counter()
                off = chunk_start
                for v in views:
                    arena_mv[off : off + v.nbytes] = v
                    off += v.nbytes
                copy_s = time.perf_counter() - t0
                chunk_view = arena_mv[chunk_start : chunk_start + nbytes]
                t1 = time.perf_counter()
                crc = zlib.crc32(chunk_view)
                crc_s = time.perf_counter() - t1
                ccrcs.append(crc)
                if changed is not None:
                    base_chunk = base_mv[chunk_start : chunk_start + nbytes]
                    if crc != base_ccrc[idx] or not np.array_equal(
                        np.frombuffer(chunk_view, np.uint8),
                        np.frombuffer(base_chunk, np.uint8),
                    ):
                        changed.append(idx)
                d2h_s = timings["d2h"] - d2h_prev
                d2h_prev = timings["d2h"]
                copy_total += copy_s
                crc_total += crc_s
                if ready is not None:
                    item = (
                        idx, crc, [chunk_view], time.perf_counter(),
                        d2h_s, copy_s, crc_s,
                    )
                    loop.call_soon_threadsafe(_resolve_ready, ready[idx], item)
                idx += 1
                chunk_start += nbytes
        except BaseException as e:
            if ready is not None:
                for fut in ready[idx:]:
                    loop.call_soon_threadsafe(_fail_ready, fut, e)
            raise
        if not ccrcs:  # empty payload: mirror wire.chunk_crcs
            ccrcs = [zlib.crc32(b"")]
        return ccrcs, changed, (timings["d2h"], copy_total, crc_total)

    @staticmethod
    def _ready_chunks(loop, full, ccrcs, indices, csz, total):
        """Pre-resolved ready futures over an already-snapshotted
        payload (delta ship / retry of a produced arena)."""
        now = time.perf_counter()
        ready = []
        for i in indices:
            size = min(csz, total - i * csz)
            fut = loop.create_future()
            fut.set_result(
                (i, ccrcs[i], [full[i * csz : i * csz + size]], now,
                 0.0, 0.0, 0.0)
            )
            ready.append(fut)
        return ready

    async def _send_striped_frames(
        self, base_header, total, csz, nch, ready, base_fp=None,
    ) -> Dict[str, Any]:
        """Ship one payload as per-chunk stripe frames fanned
        round-robin across the rails (wire v4).

        Each ready item carries its logical chunk index; ``base_fp``
        non-None marks the frames as a delta against the receiver's
        cached base.  On any frame failure every other rail drains
        before the error surfaces — the payload fails (and retries) as
        a unit.  Returns the completing frame's ACK header.
        """
        nf = len(ready)
        sid = next(self._sid)
        rails = await self._acquire_rails(min(self._stripe_rails(), nf))

        async def _one(pos: int, conn: _Conn):
            idx, crc, views, t_ready, d2h_s, copy_s, crc_s = await ready[pos]
            st = self.stats
            st["send_d2h_s"] += d2h_s
            st["send_copy_s"] += copy_s
            st["send_crc_s"] += crc_s
            st["send_prepare_s"] += d2h_s + copy_s + crc_s
            loop_wait_s = max(0.0, time.perf_counter() - t_ready)
            st["send_loop_wait_s"] += loop_wait_s
            self._bill_backend(
                d2h=d2h_s, copy=copy_s, crc=crc_s, loop_wait=loop_wait_s
            )
            hdr = dict(base_header)
            hdr["ccrc"] = [crc]
            hdr["dlt"] = wire.make_delta_manifest(
                total, wire.encode_chunk_bitmap([idx], nch), base_fp
            )
            hdr["stp"] = wire.make_stripe_marker(sid, nf)
            ack = await self._roundtrip(wire.MSG_DATA, hdr, views, conn=conn)
            st["send_stripe_frames"] += 1
            return ack

        results = await asyncio.gather(
            *(_one(pos, rails[pos % len(rails)]) for pos in range(nf)),
            return_exceptions=True,
        )
        errs = [r for r in results if isinstance(r, BaseException)]
        if errs:
            for kind in (FatalSendError, DeltaBaseError):
                for e in errs:
                    if isinstance(e, kind):
                        raise e
            for e in errs:
                if isinstance(e, asyncio.TimeoutError):
                    raise e
            e0 = errs[0]
            if isinstance(e0, (SendError, OSError, ConnectionError,
                               asyncio.CancelledError)):
                raise e0
            raise SendError(
                f"striped payload to {self._dest_party} failed: {e0!r}"
            ) from e0
        for ack in results:
            if ack.get("result") == "OK":
                self.stats["send_striped_payloads"] += 1
                return ack
        # Every frame ACKed "SEG" but none completed the assembly: the
        # receiver lost it mid-group (evicted under memory pressure /
        # idle-dropped).  This is NOT a delivery — treating it as one
        # would hang the consumer's rendezvous and (on stream sends)
        # corrupt the delta-base contract.  Surface as retryable: the
        # retry re-ships the whole payload under a fresh sid.
        raise SendError(
            f"striped payload to {self._dest_party} completed without a "
            f"delivery ACK (receiver dropped the assembly mid-group); "
            f"retrying"
        )

    async def _send_plain_striped(
        self, header, payload_bufs, payload_len
    ) -> str:
        """Non-stream large payload as multi-rail stripe frames.

        Chunks are cut as zero-copy views over the (lazily produced)
        payload buffers — no arena copy, since nothing diffs against
        these bytes later — and ship as soon as produced: the single
        payload that used to ride one socket behind a full-payload
        encode/CRC barrier now saturates the whole connection pool.
        """
        loop = asyncio.get_running_loop()
        csz = wire.DELTA_CHUNK_BYTES
        nch = max(1, -(-payload_len // csz))
        base_header = dict(header)
        base_header["ccsz"] = csz
        policy = self._retry_policy
        backoff: Optional[float] = None
        last_exc: Optional[Exception] = None
        for attempt in range(max(1, policy.max_attempts)):
            if attempt:
                if self._dest_known_dead():
                    self._dead_fast_fail(last_exc)
                backoff = policy.next_backoff(backoff)
                logger.debug(
                    "[%s] retrying striped send to %s in %.2fs "
                    "(attempt %d/%d)",
                    self._src_party, self._dest_party, backoff,
                    attempt + 1, policy.max_attempts,
                )
                await asyncio.sleep(backoff)
            import threading as _threading

            ready = [loop.create_future() for _ in range(nch)]
            abort = _threading.Event()
            producer = loop.run_in_executor(
                None, self._produce_plain_chunks, loop, payload_bufs, csz,
                ready, abort,
            )
            try:
                ack = await self._send_striped_frames(
                    base_header, payload_len, csz, nch, ready
                )
                return ack.get("result", "OK")
            except FatalSendError:
                raise
            except asyncio.TimeoutError as e:
                raise SendError(
                    f"send to {self._dest_party} timed out after "
                    f"{self._timeout_s}s"
                ) from e
            except (SendError, OSError, ConnectionError) as e:
                last_exc = e
                logger.debug(
                    "[%s] striped send to %s attempt %d/%d failed: %s",
                    self._src_party, self._dest_party, attempt + 1,
                    policy.max_attempts, e,
                )
            finally:
                # Stop production at the next chunk boundary: a failed
                # attempt must not make its retry wait out the rest of
                # a dead payload's fetch+CRC pass.  (After success the
                # producer has already finished — the final frame could
                # not ship without the last chunk.)
                abort.set()
                await producer  # never raises: failures land on `ready`
                for fut in ready:
                    if fut.done() and not fut.cancelled():
                        fut.exception()  # mark retrieved
                    elif not fut.done():
                        fut.cancel()
        raise SendError(
            f"striped send to {self._dest_party} failed after "
            f"{policy.max_attempts} attempts: {last_exc}"
        )

    async def send_data(
        self,
        payload_bufs: List,
        upstream_seq_id: str,
        downstream_seq_id: str,
        metadata: Optional[Dict[str, str]] = None,
        crc: Optional[int] = None,
        error: Optional[Dict[str, str]] = None,
        stream: Optional[str] = None,
        stream_snapshot: Optional[tuple] = None,
    ) -> str:
        """See :meth:`_send_data_impl` — this wrapper only maintains the
        whole-operation in-flight count :meth:`has_inflight_sends`
        reads (the message-cap mutation guard) and the chaos "send"
        hook (whole-payload delay/drop injection)."""
        if chaos.installed() is not None:
            await chaos.fire_async(
                "send", party=self._src_party, dest=self._dest_party,
                stream=stream, up=str(upstream_seq_id),
                down=str(downstream_seq_id),
            )
        self._inflight_sends += 1
        try:
            if not self._local_decided:
                await self._ensure_local_backend()
            if self._link_backend == "shm" and self._local_endpoint is not None:
                return await self._send_shm(
                    payload_bufs, upstream_seq_id, downstream_seq_id,
                    metadata=metadata, crc=crc, error=error,
                    stream_snapshot=stream_snapshot,
                )
            return await self._send_data_impl(
                payload_bufs, upstream_seq_id, downstream_seq_id,
                metadata=metadata, crc=crc, error=error, stream=stream,
                stream_snapshot=stream_snapshot,
            )
        finally:
            self._inflight_sends -= 1

    async def _shm_roundtrip(
        self, msg_type: int, header: Dict[str, Any], payload,
        timeout_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """One in-process frame handoff, with the socket path's chaos
        semantics: the "wire" hook fires on every frame, "frame" on DATA
        (its mutable header is how corrupt_crc plants a wrong declared
        checksum the receiver's mismatch path then catches)."""
        if chaos.installed() is not None:
            await chaos.fire_async(
                "wire", party=self._src_party, dest=self._dest_party,
                type=msg_type,
            )
        header = dict(header, rid=next(self._rid))
        if msg_type == wire.MSG_DATA and chaos.installed() is not None:
            await chaos.fire_async(
                "frame", party=self._src_party, dest=self._dest_party,
                header=header,
            )
        return await local.deliver(
            self._local_endpoint, msg_type, header, payload,
            self._timeout_s if timeout_s is None else timeout_s,
        )

    async def _send_shm(
        self,
        payload_bufs: List,
        upstream_seq_id: str,
        downstream_seq_id: str,
        metadata: Optional[Dict[str, str]] = None,
        crc: Optional[int] = None,
        error: Optional[Dict[str, str]] = None,
        stream_snapshot: Optional[tuple] = None,
    ) -> str:
        """Same-process delivery: one gather copy, zero socket writes.

        The payload is materialized into ONE freshly-allocated buffer
        (or a fan-out's shared snapshot is passed as-is — also fresh
        per send) and handed to the destination server BY REFERENCE;
        per-chunk CRC and the delta cache are bypassed — diff passes
        and checksums that save wire bytes are pure loss when there is
        no wire, so stream sends ship full payloads here and the
        ``delta_*`` counters intentionally stay still.  Delivery
        semantics match the socket path: retry ladder, ACK deadline
        (non-retried), epoch rejects, chunk sinks, telemetry.
        """
        total = wire.payload_nbytes(payload_bufs)
        if total > self._max_message_size:
            raise SendError(
                f"message of {total} bytes exceeds configured max "
                f"{self._max_message_size}"
            )
        merged_meta = dict(self._metadata)
        if metadata:
            merged_meta.update(metadata)
        base_header: Dict[str, Any] = {
            "src": self._src_party,
            "up": str(upstream_seq_id),
            "down": str(downstream_seq_id),
            "meta": merged_meta,
        }
        if error is not None:
            base_header["err"] = error
        if crc is not None and self._checksum:
            # Pinned-checksum links keep the precomputed digest (the
            # receiver verifies it); elided links drop it.
            base_header["crc"] = crc
        loop = asyncio.get_running_loop()
        t_frame0 = time.perf_counter()
        if stream_snapshot is not None:
            payload: Any = stream_snapshot[0]
            d2h_s = copy_s = 0.0  # billed to the fan-out's codec pass
        elif 0 < total <= _INLINE_MATERIALIZE_BYTES:
            # Small payload: the executor round trip (two thread hops +
            # a GIL handoff each) costs more than the copy itself — at
            # N=64 virtual parties the hierarchy round hands off ~2k
            # stripe-sized frames, all under this bound.
            payload, d2h_s, copy_s = local.materialize(payload_bufs)
        elif total:
            payload, d2h_s, copy_s = await loop.run_in_executor(
                None, local.materialize, payload_bufs
            )
        else:
            payload, d2h_s, copy_s = bytearray(0), 0.0, 0.0
        policy = self._retry_policy
        backoff: Optional[float] = None
        last_exc: Optional[Exception] = None
        for attempt in range(max(1, policy.max_attempts)):
            if attempt:
                if self._dest_known_dead():
                    self._dead_fast_fail(last_exc)
                backoff = policy.next_backoff(backoff)
                logger.debug(
                    "[%s] retrying shm send to %s in %.2fs (attempt %d/%d)",
                    self._src_party, self._dest_party, backoff,
                    attempt + 1, policy.max_attempts,
                )
                await asyncio.sleep(backoff)
            t_hand = time.perf_counter()
            try:
                ack = await self._shm_roundtrip(
                    wire.MSG_DATA, base_header, payload
                )
            except FatalSendError:
                raise
            except asyncio.TimeoutError as e:
                raise SendError(
                    f"send to {self._dest_party} timed out after "
                    f"{self._timeout_s}s"
                ) from e
            except (SendError, OSError, ConnectionError) as e:
                last_exc = e
                logger.debug(
                    "[%s] shm send to %s attempt %d/%d failed: %s",
                    self._src_party, self._dest_party, attempt + 1,
                    policy.max_attempts, e,
                )
                continue
            handoff_s = time.perf_counter() - t_hand
            st = self.stats
            st["send_frames"] += 1
            st["send_payload_bytes"] += total
            st["send_prepare_s"] += d2h_s + copy_s
            st["send_d2h_s"] += d2h_s
            st["send_copy_s"] += copy_s
            st["send_socket_s"] += handoff_s
            self._bill_backend(
                backend="shm", d2h=d2h_s, copy=copy_s, socket=handoff_s
            )
            frame_wall = time.perf_counter() - t_frame0
            st["send_frame_wall_s"] += frame_wall
            _tr = telemetry.active()
            if _tr is not None:
                _tr.emit(
                    "wire.frame", party=self._src_party,
                    peer=self._dest_party, nbytes=total,
                    t_start=time.time() - frame_wall, dur_s=frame_wall,
                    detail={
                        "backend": "shm",
                        "d2h_ms": round(d2h_s * 1e3, 3),
                        "crc_ms": 0.0,
                        "socket_ms": round(handoff_s * 1e3, 3),
                    },
                )
            return ack.get("result", "OK")
        raise SendError(
            f"send to {self._dest_party} failed after "
            f"{policy.max_attempts} attempts: {last_exc}"
        )

    async def _send_data_impl(
        self,
        payload_bufs: List,
        upstream_seq_id: str,
        downstream_seq_id: str,
        metadata: Optional[Dict[str, str]] = None,
        crc: Optional[int] = None,
        error: Optional[Dict[str, str]] = None,
        stream: Optional[str] = None,
        stream_snapshot: Optional[tuple] = None,
    ) -> str:
        """Push one DATA message with retry policy; returns the ACK result.

        ``error``: poison the rendezvous key instead of delivering data —
        the consumer's recv raises :class:`~rayfed_tpu.exceptions.RemoteError`
        (improves on reference ``barriers.py:244-248`` which leaves the
        consumer parked with no diagnosis).

        ``stream``: name a logical stream (stable across rounds, e.g.
        ``"fedavg/alice"``) to enable the per-peer delta cache: the
        payload is diffed against the last payload the peer ACKed on the
        stream and only changed :data:`wire.DELTA_CHUNK_BYTES` ranges
        ship (plus a bitmap manifest + per-chunk CRCs — wire format v3).
        ``stream_snapshot``: a precomputed
        :meth:`snapshot_stream_payload` result, shared across a fan-out
        so the payload is materialized and hashed once, not once per
        destination.
        """
        if stream is not None and error is None:
            return await self._send_stream(
                stream, payload_bufs, upstream_seq_id, downstream_seq_id,
                metadata, snapshot=stream_snapshot,
            )
        payload_len = wire.payload_nbytes(payload_bufs)
        if payload_len > self._max_message_size:
            raise SendError(
                f"message of {payload_len} bytes exceeds configured max "
                f"{self._max_message_size}"
            )
        merged_meta = dict(self._metadata)
        if metadata:
            merged_meta.update(metadata)
        header = {
            "src": self._src_party,
            "up": str(upstream_seq_id),
            "down": str(downstream_seq_id),
            "meta": merged_meta,
        }
        if error is not None:
            header["err"] = error
        if (
            error is None
            and payload_len >= wire.STRIPE_MIN_BYTES
            and self._stripe_rails() >= 2
        ):
            # Multi-rail striping (wire v4): the payload's chunks fan
            # out round-robin across the connection pool as per-chunk
            # frames — one large payload no longer rides one socket,
            # and the fetch/CRC of chunk k+1 overlaps the write of
            # chunk k with no full-payload serialization barrier.
            return await self._send_plain_striped(
                header, payload_bufs, payload_len
            )
        has_lazy = any(isinstance(b, wire.LazyBuffer) for b in payload_bufs)
        streamed = has_lazy or payload_len >= wire.SHARD_STREAM_THRESHOLD
        crc_trailer = False
        if crc is None and self._checksum and streamed:
            # Streamed payload (lazy shards, or big enough to chunk):
            # the checksum chains incrementally during the write —
            # overlapped with the socket, per chunk — and rides a
            # trailer, not the header.
            crc_trailer = True
        elif crc is None and self._checksum:
            # Prefer passing ``crc`` precomputed off-loop (the manager's
            # codec pool does) — this inline path serves direct callers.
            from rayfed_tpu import native

            crc = native.crc32c_multi(payload_bufs)
        if crc is not None:
            header["crc"] = crc
        policy = self._retry_policy
        backoff: Optional[float] = None
        last_exc: Optional[Exception] = None
        for attempt in range(max(1, policy.max_attempts)):
            if attempt:
                if self._dest_known_dead():
                    self._dead_fast_fail(last_exc)
                # Decorrelated jitter (policy.jitter, default on): N
                # parties retrying the same dead peer must not wake in
                # lockstep.  The chosen delay is logged so a retry storm
                # is diagnosable from one party's logs.
                backoff = policy.next_backoff(backoff)
                logger.debug(
                    "[%s] retrying send to %s in %.2fs (attempt %d/%d)",
                    self._src_party, self._dest_party, backoff,
                    attempt + 1, policy.max_attempts,
                )
                await asyncio.sleep(backoff)
            try:
                ack = await self._roundtrip(
                    wire.MSG_DATA, header, payload_bufs, crc_trailer=crc_trailer
                )
                return ack.get("result", "OK")
            except FatalSendError:
                raise
            except asyncio.TimeoutError as e:
                # Deadline exceeded is not retried (parity: only UNAVAILABLE
                # is a retryable status in the reference policy).  Must
                # precede the retry arm: TimeoutError subclasses OSError
                # since 3.10.
                raise SendError(
                    f"send to {self._dest_party} timed out after "
                    f"{self._timeout_s}s"
                ) from e
            except (SendError, OSError, ConnectionError) as e:
                last_exc = e
                logger.debug(
                    "[%s] send to %s attempt %d/%d failed: %s",
                    self._src_party, self._dest_party, attempt + 1,
                    policy.max_attempts, e,
                )
        raise SendError(
            f"send to {self._dest_party} failed after "
            f"{policy.max_attempts} attempts: {last_exc}"
        )

    def send_data_async(
        self,
        payload_bufs: List,
        upstream_seq_id: str,
        downstream_seq_id: str,
        **kwargs,
    ):
        """Thread-safe, non-blocking :meth:`send_data`: returns a
        completion future instead of awaiting the ACK.

        The returned :class:`~rayfed_tpu.executor.LocalRef` resolves to
        the ACK result string once the peer acknowledged the FINAL frame
        of the send (for delta streams that includes any transparent
        full-payload re-seed after a ``delta_base`` desync), and errs
        with the send's failure — peer death after retries, a re-seed
        that itself failed, an oversize payload.  Callable from any
        thread; the client must have been constructed with its event
        loop bound (``loop=``; :class:`TransportManager` always does).
        Accepts every :meth:`send_data` keyword (``metadata``, ``crc``,
        ``stream``, ``stream_snapshot``, ``error``).
        """
        from rayfed_tpu.executor import LocalRef

        if self._loop is None:
            raise RuntimeError(
                "send_data_async needs the client's event loop bound at "
                "construction (loop=...); direct awaiters use send_data"
            )
        cf = asyncio.run_coroutine_threadsafe(
            self.send_data(
                payload_bufs, upstream_seq_id, downstream_seq_id, **kwargs
            ),
            self._loop,
        )
        out = LocalRef()

        def _done(f):
            if f.cancelled():
                out.set_exception(SendError("client send cancelled"))
            elif f.exception() is not None:
                out.set_exception(f.exception())
            else:
                out.set_result(f.result())

        cf.add_done_callback(_done)
        return out

    @staticmethod
    def snapshot_stream_payload(payload_bufs: List):
        """Materialize the payload contiguously + its chunk CRCs.

        Delta diffing needs a stable byte snapshot of the whole payload
        (lazy shards are forced here), so stream sends trade the
        overlapped per-shard fetch for the ability to skip unchanged
        chunks entirely — the right trade when most chunks repeat.
        Static so a fan-out (``TransportManager.send_many``) computes it
        ONCE and shares it with every destination's client; run it on a
        codec/executor thread, not the event loop."""
        from rayfed_tpu import native

        views = []
        for buf in payload_bufs:
            host = buf.produce() if isinstance(buf, wire.LazyBuffer) else buf
            mv = host if isinstance(host, memoryview) else memoryview(host)
            if mv.format != "B":
                mv = mv.cast("B")
            views.append(mv)
        full = native.gather_copy(views)
        return full, wire.chunk_crcs(full)

    @staticmethod
    def _diff_chunks(full, base, ccrcs, base_ccrcs) -> List[int]:
        """Indices of DELTA_CHUNK_BYTES chunks that differ from the base.

        CRC inequality proves difference; CRC equality is confirmed with
        a vectorized byte compare (a colliding chunk must not be
        silently dropped from the delta)."""
        import numpy as np

        csz = wire.DELTA_CHUNK_BYTES
        a = np.frombuffer(full, dtype=np.uint8)
        b = np.frombuffer(base, dtype=np.uint8)
        changed = []
        for i, (c_new, c_old) in enumerate(zip(ccrcs, base_ccrcs)):
            off = i * csz
            if c_new != c_old or not np.array_equal(
                a[off : off + csz], b[off : off + csz]
            ):
                changed.append(i)
        return changed

    async def _send_stream(
        self, stream: str, payload_bufs: List, upstream_seq_id: str,
        downstream_seq_id: str, metadata: Optional[Dict[str, str]],
        snapshot: Optional[tuple] = None,
    ) -> str:
        """Stream send with the per-peer delta cache (wire v3/v4).

        The payload is snapshotted into the stream's reusable
        page-aligned send arena (ping-pong slots: the last-ACKed base
        stays byte-stable in the other slot and the delta diff aliases
        both — no per-round alloc+concat+copy), per-chunk CRC'd and
        diffed against the base in the SAME pass, then shipped one of
        three ways:

        - unchanged / small delta → the single-frame wire-v3 delta path;
        - large delta (≥ 2 rails) → the changed chunks striped across
          the rails;
        - fresh/full payload ≥ :data:`wire.STRIPE_MIN_BYTES` with ≥ 2
          rails → pipelined stripe frames: chunk k is on a socket while
          chunk k+1 is still being fetched and CRC'd (no full-payload
          serialization barrier).

        A ``delta_base`` reply (receiver restarted / base desynced)
        falls back to a full payload, re-seeding both caches."""
        state = self._delta_streams.setdefault(stream, _DeltaStream())
        self._delta_streams.move_to_end(stream)
        if len(self._delta_streams) > _MAX_DELTA_STREAMS:
            # Oldest UNLOCKED stream loses its base (it re-seeds with a
            # full payload on next use).  A locked state has a send in
            # flight — evicting it would let a second _DeltaStream for
            # the same name race the serialization its lock promises.
            for key in list(self._delta_streams):
                if len(self._delta_streams) <= _MAX_DELTA_STREAMS:
                    break
                if key != stream and not self._delta_streams[key].lock.locked():
                    del self._delta_streams[key]
        loop = asyncio.get_running_loop()
        async with state.lock:
            csz = wire.DELTA_CHUNK_BYTES
            total = wire.payload_nbytes(payload_bufs)
            if total > self._max_message_size:
                raise SendError(
                    f"message of {total} bytes exceeds configured max "
                    f"{self._max_message_size}"
                )
            nch = max(1, -(-total // csz))
            merged_meta = dict(self._metadata)
            if metadata:
                merged_meta.update(metadata)
            base_header = {
                "src": self._src_party,
                "up": str(upstream_seq_id),
                "down": str(downstream_seq_id),
                "meta": merged_meta,
                "stm": stream,
                "ccsz": csz,
            }
            has_base = (
                state.data is not None
                and state.ccrc is not None
                and len(state.data) == total
            )
            # Stripe only with >= 2 rails: on one rail the per-chunk
            # frames still pay per-frame ACKs and the receiver's
            # reassembly memcpy with nothing pipelining against them —
            # the v3 single-frame path (below) already overlaps CRC
            # with the socket and delivers zero-copy, and it now snaps
            # into the reusable arena too.
            stripeable = (
                total >= wire.STRIPE_MIN_BYTES
                and nch >= 2
                and self._stripe_rails() >= 2
            )
            full: Optional[memoryview] = None
            ccrcs: Optional[List[int]] = None
            changed: Optional[List[int]] = None
            pipelined = False
            if snapshot is not None:
                # Fan-out path: ONE shared snapshot + CRC pass serves
                # every destination (codec thread); only the diff
                # against THIS destination's base runs here.
                full_raw, ccrcs = snapshot
                full = memoryview(full_raw)
                if full.format != "B":
                    full = full.cast("B")
                if has_base:
                    changed = await loop.run_in_executor(
                        None, self._diff_chunks, full, state.data, ccrcs,
                        state.ccrc,
                    )
            elif has_base or not stripeable:
                # Arena snapshot: copy + CRC + diff in ONE executor
                # pass over the reused mmap arena.
                arena_mv = state.writable_arena(total)
                ccrcs, changed, totals = await loop.run_in_executor(
                    None, self._produce_arena_chunks, loop, payload_bufs,
                    arena_mv, csz,
                    state.data if has_base else None,
                    state.ccrc if has_base else None,
                    None,
                )
                full = arena_mv
                st = self.stats
                st["send_d2h_s"] += totals[0]
                st["send_copy_s"] += totals[1]
                st["send_crc_s"] += totals[2]
                st["send_prepare_s"] += sum(totals)
                self._bill_backend(
                    d2h=totals[0], copy=totals[1], crc=totals[2]
                )
            else:
                # Fresh stripe-sized payload: production is pipelined
                # with the stripe frames inside the attempt loop.
                full = state.writable_arena(total)
                pipelined = True

            # A delta frame only wins when at least one chunk is skipped.
            force_full = changed is None or len(changed) >= nch
            policy = self._retry_policy
            backoff: Optional[float] = None
            last_exc: Optional[Exception] = None
            attempt = 0
            import threading as _threading

            while attempt < max(1, policy.max_attempts):
                producer = None
                abort = _threading.Event()
                ready: Optional[List[asyncio.Future]] = None
                try:
                    if force_full and stripeable:
                        if pipelined and ccrcs is None:
                            ready = [
                                loop.create_future() for _ in range(nch)
                            ]
                            producer = loop.run_in_executor(
                                None, self._produce_arena_chunks, loop,
                                payload_bufs, full, csz, None, None, ready,
                                abort,
                            )
                        else:
                            ready = self._ready_chunks(
                                loop, full, ccrcs, list(range(nch)), csz,
                                total,
                            )
                        ack = await self._send_striped_frames(
                            base_header, total, csz, nch, ready
                        )
                    elif (
                        not force_full
                        and len(changed) >= 2
                        and len(changed) * csz >= wire.STRIPE_MIN_BYTES
                        and self._stripe_rails() >= 2
                    ):
                        # Big delta: changed chunks fan out over the
                        # rails too (same reassembly machinery, with
                        # the base fingerprint carried per frame).
                        ready = self._ready_chunks(
                            loop, full, ccrcs, changed, csz, total
                        )
                        ack = await self._send_striped_frames(
                            base_header, total, csz, nch, ready,
                            base_fp=state.fp,
                        )
                    else:
                        header = dict(base_header)
                        if not force_full:
                            header["ccrc"] = [ccrcs[i] for i in changed]
                            header["dlt"] = wire.make_delta_manifest(
                                total,
                                wire.encode_chunk_bitmap(changed, nch),
                                state.fp,
                            )
                            bufs = [
                                full[i * csz : (i + 1) * csz]
                                for i in changed
                            ]
                        else:
                            header["ccrc"] = ccrcs
                            bufs = [full] if total else []
                        ack = await self._roundtrip(
                            wire.MSG_DATA, header, bufs
                        )
                except DeltaBaseError:
                    if force_full:  # full sends can't need a base
                        raise
                    logger.debug(
                        "[%s] stream %r delta base desynced at %s; "
                        "re-seeding with a full payload",
                        self._src_party, stream, self._dest_party,
                    )
                    force_full = True  # immediate, not a failed attempt
                    continue
                except FatalSendError:
                    raise
                except asyncio.TimeoutError as e:
                    raise SendError(
                        f"send to {self._dest_party} timed out after "
                        f"{self._timeout_s}s"
                    ) from e
                except (SendError, OSError, ConnectionError) as e:
                    # Outcome unknown (e.g. applied but ACK lost): the
                    # cache keeps the last-ACKED base — if the peer in
                    # fact advanced, the next delta's bfp mismatches and
                    # the delta_base fallback re-seeds.  Retry per
                    # policy.
                    last_exc = e
                    attempt += 1
                    logger.debug(
                        "[%s] stream send to %s attempt %d/%d failed: %s",
                        self._src_party, self._dest_party, attempt,
                        policy.max_attempts, e,
                    )
                    if attempt >= max(1, policy.max_attempts):
                        break
                    if self._dest_known_dead():
                        self._dead_fast_fail(last_exc)
                    backoff = policy.next_backoff(backoff)
                    logger.debug(
                        "[%s] retrying stream send to %s in %.2fs",
                        self._src_party, self._dest_party, backoff,
                    )
                    await asyncio.sleep(backoff)
                    continue
                finally:
                    if producer is not None:
                        # Stop production at the next chunk boundary on
                        # failure; after success the producer already
                        # finished (the final frame needed its chunk).
                        abort.set()
                        try:
                            ccrcs, _chg, _totals = await producer
                        except Exception:
                            ccrcs = None  # re-produce on the retry
                        if ready is not None:
                            for fut in ready:
                                if fut.done() and not fut.cancelled():
                                    fut.exception()  # mark retrieved
                                elif not fut.done():
                                    fut.cancel()
                # ACKed: the peer now holds `full` — it IS the new base.
                wire_bytes = (
                    total if force_full
                    else sum(min(csz, total - i * csz) for i in changed)
                )
                state.data = full
                state.ccrc = ccrcs
                state.fp = wire.crc_fingerprint(ccrcs)
                self.stats["delta_logical_bytes"] += total
                self.stats["delta_wire_bytes"] += wire_bytes
                if force_full:
                    self.stats["delta_full_frames"] += 1
                else:
                    self.stats["delta_stream_frames"] += 1
                _tr = telemetry.active()
                if _tr is not None:
                    # Delta-cache verdict for THIS stream send: how many
                    # of the payload's chunks the diff kept off the wire
                    # (a "full" outcome is a cold stream or a re-seed
                    # after a base desync).  Ring append only — loop
                    # coroutine.
                    _tr.emit(
                        "wire.delta", party=self._src_party,
                        peer=self._dest_party, stream=stream,
                        nbytes=wire_bytes,
                        outcome="full" if force_full else "delta",
                        detail={
                            "logical_bytes": total,
                            "changed_chunks": (
                                None if force_full else len(changed)
                            ),
                        },
                    )
                return ack.get("result", "OK")
            raise SendError(
                f"stream send to {self._dest_party} failed after "
                f"{policy.max_attempts} attempts: {last_exc}"
            )

    async def ping(self, timeout_s: float = 1.0, ctl: bool = False) -> bool:
        """Readiness probe with a per-request deadline (no shared-state
        mutation — concurrent sends keep their own timeout).

        ``ctl=True`` (the health monitor): ride the dedicated control
        connection so the probe cannot queue behind a bulk payload write
        on the data pool — which would read as "dead" exactly when the
        peer is busiest.  Default (one-shot readiness pings): use the
        data pool, warming a connection the first real send then reuses,
        and leaving no extra long-lived socket behind when no monitor
        runs."""
        try:
            if not self._local_decided:
                await self._ensure_local_backend()
            if self._link_backend == "shm" and self._local_endpoint is not None:
                if chaos.installed() is None:
                    # In-process peer: liveness is a registry verdict,
                    # not a roundtrip — N virtual parties' health
                    # monitors each ping every monitored peer per tick,
                    # an O(N²) control storm that was ~a third of the
                    # N=64 hierarchy round wall; and a ping DEADLINE
                    # under GIL starvation reads busy as dead exactly
                    # when the process is loaded.
                    return local.endpoint_alive(self._local_endpoint)
                # Chaos armed: ride the handoff so an injected
                # partition starves the PONG exactly like on a wire.
                await self._shm_roundtrip(
                    wire.MSG_PING, {"src": self._src_party}, b"",
                    timeout_s=timeout_s,
                )
                return True
            conn = await self._acquire_ctl_conn() if ctl else None
            await self._roundtrip(
                wire.MSG_PING, {"src": self._src_party}, [],
                timeout_s=timeout_s, conn=conn,
            )
            return True
        except Exception:
            return False
