"""Transport client — persistent, multiplexed, retrying connection per peer.

Plays the role of the reference's ``send_data_grpc`` channel
(``barriers.py:121-181``) plus its gRPC service-config retry policy
(``grpc_options.py:17-23``): attempts with exponential backoff on
transport unavailability, a per-RPC deadline, per-party metadata headers,
and a message-size cap.  One connection per destination party carries
pipelined DATA frames; ACKs are matched by request id.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import ssl
from typing import Any, Dict, List, Optional

from rayfed_tpu.config import RetryPolicy
from rayfed_tpu.transport import wire

logger = logging.getLogger(__name__)


class SendError(ConnectionError):
    pass


class FatalSendError(SendError):
    """A send rejected by the peer for a non-transient reason — not retried."""


class TransportClient:
    def __init__(
        self,
        src_party: str,
        dest_party: str,
        address: str,
        retry_policy: RetryPolicy,
        timeout_s: float,
        max_message_size: int,
        metadata: Optional[Dict[str, str]] = None,
        ssl_context: Optional[ssl.SSLContext] = None,
        server_hostname: Optional[str] = None,
        checksum: Optional[bool] = None,
    ) -> None:
        if checksum is None:
            # Match the manager's policy: checksum only when the fast C++
            # CRC path is built.  A directly-constructed client otherwise
            # pays a ~MB/s pure-Python CRC on the event loop for a digest
            # that a native-less receiver skips verifying anyway.
            from rayfed_tpu import native

            checksum = native.is_available()
        self._checksum = checksum
        self._src_party = src_party
        self._dest_party = dest_party
        host, _, port = address.rpartition(":")
        self._host = host
        self._port = int(port)
        self._retry_policy = retry_policy
        self._timeout_s = timeout_s
        self._max_message_size = max_message_size
        self._metadata = dict(metadata or {})
        self._ssl_context = ssl_context
        self._server_hostname = server_hostname
        self._rid = itertools.count(1)
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._conn_lock = asyncio.Lock()
        self._write_lock = asyncio.Lock()

    # -- connection management ------------------------------------------------

    async def _ensure_connected(self) -> None:
        if self._writer is not None and not self._writer.is_closing():
            return
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            reader, writer = await asyncio.open_connection(
                self._host,
                self._port,
                ssl=self._ssl_context,
                server_hostname=self._server_hostname if self._ssl_context else None,
                limit=2**20,
            )
            self._reader = reader
            self._writer = writer
            self._reader_task = asyncio.ensure_future(self._read_responses(reader))

    async def _read_responses(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                prefix = await reader.readexactly(wire.HEADER_SIZE)
                msg_type, _flags, hlen, plen = wire.unpack_frame_prefix(prefix)
                header = json.loads(await reader.readexactly(hlen)) if hlen else {}
                if plen:
                    await reader.readexactly(plen)
                rid = header.get("rid")
                fut = self._pending.pop(rid, None)
                if fut is None or fut.done():
                    continue
                if msg_type == wire.MSG_ERR:
                    exc_cls = FatalSendError if header.get("fatal") else SendError
                    fut.set_exception(exc_cls(header.get("error", "remote error")))
                else:
                    fut.set_result(header)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError) as e:
            self._fail_pending(SendError(f"connection to {self._dest_party} lost: {e}"))
        except asyncio.CancelledError:
            self._fail_pending(SendError("client shutting down"))
            raise

    def _fail_pending(self, exc: Exception) -> None:
        pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(exc)
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
        self._writer = None
        self._reader = None

    async def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
            try:
                await self._reader_task
            except (asyncio.CancelledError, Exception):
                pass
            self._reader_task = None
        self._fail_pending(SendError("client closed"))

    # -- RPCs -----------------------------------------------------------------

    async def _roundtrip(
        self, msg_type: int, header: Dict[str, Any], payload_bufs: List,
        crc_trailer: bool = False,
    ) -> Dict[str, Any]:
        await self._ensure_connected()
        rid = next(self._rid)
        header = dict(header, rid=rid)
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending[rid] = fut
        payload_len = wire.payload_nbytes(payload_bufs)
        flags = wire.FLAG_CRC_TRAILER if crc_trailer else 0
        try:
            async with self._write_lock:
                assert self._writer is not None
                for buf in wire.pack_frame(msg_type, header,
                                           payload_len=payload_len,
                                           flags=flags):
                    self._writer.write(buf)
                await self._write_payload(loop, payload_bufs, crc_trailer)
                await self._writer.drain()
            return await asyncio.wait_for(fut, timeout=self._timeout_s)
        except SendError:
            # App-level MSG_ERR reply for THIS request (e.g. checksum
            # mismatch, oversize).  The connection itself is healthy —
            # don't tear it down or fail the other pipelined sends.
            # (SendError subclasses ConnectionError, so this arm must
            # precede the connection-failure arm.)
            self._pending.pop(rid, None)
            raise
        except (ConnectionError, OSError, asyncio.IncompleteReadError) as e:
            self._pending.pop(rid, None)
            self._fail_pending(SendError(str(e)))
            raise SendError(str(e)) from e
        except asyncio.TimeoutError:
            self._pending.pop(rid, None)
            raise

    async def _write_payload(
        self, loop, payload_bufs: List, crc_trailer: bool
    ) -> None:
        """Write payload buffers, producing lazy shards with one-ahead
        prefetch: shard k+1's device→host fetch runs in the executor while
        shard k drains to the socket.  With ``crc_trailer``, the checksum
        chains across buffers off-loop and lands in a 4-byte trailer."""

        if crc_trailer:
            from rayfed_tpu import native

        def _materialize(buf, seed):
            host = buf.produce() if isinstance(buf, wire.LazyBuffer) else buf
            # Fetch + checksum in ONE executor hop; the chained seed makes
            # the trailer equal crc32c(concat(payload)).
            crc = native.crc32c(host, seed) if crc_trailer else 0
            return host, crc

        if not payload_bufs:
            return
        crc = 0
        prefetch = loop.run_in_executor(None, _materialize, payload_bufs[0], 0)
        for i in range(len(payload_bufs)):
            host, crc = await prefetch
            if i + 1 < len(payload_bufs):
                prefetch = loop.run_in_executor(
                    None, _materialize, payload_bufs[i + 1], crc
                )
            self._writer.write(host)
            await self._writer.drain()
        if crc_trailer:
            import struct

            self._writer.write(struct.pack(">I", crc))

    @property
    def checksum_enabled(self) -> bool:
        return self._checksum

    async def send_data(
        self,
        payload_bufs: List,
        upstream_seq_id: str,
        downstream_seq_id: str,
        metadata: Optional[Dict[str, str]] = None,
        crc: Optional[int] = None,
    ) -> str:
        """Push one DATA message with retry policy; returns the ACK result."""
        payload_len = wire.payload_nbytes(payload_bufs)
        if payload_len > self._max_message_size:
            raise SendError(
                f"message of {payload_len} bytes exceeds configured max "
                f"{self._max_message_size}"
            )
        merged_meta = dict(self._metadata)
        if metadata:
            merged_meta.update(metadata)
        header = {
            "src": self._src_party,
            "up": str(upstream_seq_id),
            "down": str(downstream_seq_id),
            "meta": merged_meta,
        }
        has_lazy = any(isinstance(b, wire.LazyBuffer) for b in payload_bufs)
        crc_trailer = False
        if has_lazy:
            # Streamed payload: the checksum chains incrementally during
            # the write and rides a trailer, not the header.
            crc_trailer = self._checksum
        elif crc is None and self._checksum:
            # Prefer passing ``crc`` precomputed off-loop (the manager's
            # codec pool does) — this inline path serves direct callers.
            from rayfed_tpu import native

            crc = native.crc32c_multi(payload_bufs)
        if crc is not None:
            header["crc"] = crc
        policy = self._retry_policy
        backoff = policy.initial_backoff_s
        last_exc: Optional[Exception] = None
        for attempt in range(max(1, policy.max_attempts)):
            if attempt:
                await asyncio.sleep(backoff)
                backoff = min(backoff * policy.backoff_multiplier,
                              policy.max_backoff_s)
            try:
                ack = await self._roundtrip(
                    wire.MSG_DATA, header, payload_bufs, crc_trailer=crc_trailer
                )
                return ack.get("result", "OK")
            except FatalSendError:
                raise
            except (SendError, OSError, ConnectionError) as e:
                last_exc = e
                logger.debug(
                    "[%s] send to %s attempt %d/%d failed: %s",
                    self._src_party, self._dest_party, attempt + 1,
                    policy.max_attempts, e,
                )
            except asyncio.TimeoutError as e:
                # Deadline exceeded is not retried (parity: only UNAVAILABLE
                # is a retryable status in the reference policy).
                raise SendError(
                    f"send to {self._dest_party} timed out after "
                    f"{self._timeout_s}s"
                ) from e
        raise SendError(
            f"send to {self._dest_party} failed after "
            f"{policy.max_attempts} attempts: {last_exc}"
        )

    async def ping(self, timeout_s: float = 1.0) -> bool:
        try:
            saved = self._timeout_s
            self._timeout_s = timeout_s
            try:
                await self._roundtrip(wire.MSG_PING, {"src": self._src_party}, [])
            finally:
                self._timeout_s = saved
            return True
        except Exception:
            return False
