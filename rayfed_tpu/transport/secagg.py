"""Secure-aggregation key agreement riding the connection HELLO.

The seed-era :mod:`rayfed_tpu.fl.secure` demo left pairwise key material
to the operator ("provision a group key somehow").  Here key agreement is
a **transport plane**: every party generates an ephemeral keypair per
process (per *session* — a ``fed.init`` lifetime), publishes the public
half in the connection HELLO handshake it already performs with every
peer (``wire.SECAGG_PUB_KEY``, a header key beside ``ver``/``src`` — no
frame-layout change), and records each peer's published half from the
HELLOs it receives (server side: the client's HELLO header; client side:
the server's HELLO reply).  One ping per pair is therefore enough to
establish both directions — :meth:`TransportManager.
ensure_secagg_peer_keys` does exactly that before the first masked
round.

From the pair state, per-(pair, session, stream, round) **mask seeds**
derive via HKDF-SHA256 (stdlib hmac) — masks are *generated, never
shipped*, and revealing one round's seed (dropout recovery,
:mod:`rayfed_tpu.fl.secagg`) reveals nothing about any other round's:
the HKDF is one-way in the pair secret.

Two key-exchange schemes, negotiated by what both builds can do:

- ``x25519`` (preferred): an ephemeral X25519 keypair via the optional
  ``cryptography`` dependency (same optional-dep posture as
  ``transport/tls.py``); the pair secret is the Diffie-Hellman exchange,
  so **no party — the aggregator included — can derive another pair's
  masks**.
- ``nonce`` (stdlib fallback, used when ``cryptography`` is absent): the
  published value is a random per-session nonce and the pair secret is
  HKDF(group key, both nonces).  The group key is operator-provisioned
  (``RAYFED_SECAGG_GROUP_KEY`` env var or :meth:`KeyAgreement.
  set_group_key`) — anyone holding it can derive every mask, so this
  mode only protects against an aggregator that does NOT hold the group
  key.  The per-session nonces still give mask freshness across runs.

The mask keystream (PRG) scheme rides the same advertisement:

- ``aes`` (preferred, ``cryptography``): AES-256-CTR keystream — fast
  and cryptographic.
- ``philox`` (stdlib+numpy fallback): the numpy Philox counter PRG
  keyed from the seed.  Deterministic and statistically strong but NOT
  a cryptographic PRG — a dev/test fallback, loudly documented in
  ``docs/source/secure_aggregation.rst``.

Masks only cancel when both endpoints expand the identical keystream,
so a pair whose advertised suites disagree fails **loudly** at seed
derivation instead of silently folding garbage (``RAYFED_SECAGG_PRG``
pins the scheme when a mixed cluster must align downward).
"""

from __future__ import annotations

import hashlib
import hmac
import logging
import os
import threading
import time
from typing import Dict, Optional, Tuple

logger = logging.getLogger(__name__)

try:  # optional dependency, like transport/tls.py
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        PublicFormat,
    )

    HAVE_X25519 = True
except ImportError:  # pragma: no cover - exercised on stdlib-only builds
    HAVE_X25519 = False

try:
    from cryptography.hazmat.primitives.ciphers import (  # noqa: F401
        Cipher,
        algorithms,
        modes,
    )

    HAVE_AES = True
except ImportError:  # pragma: no cover - exercised on stdlib-only builds
    HAVE_AES = False

# Version of the secagg HELLO-value format AND of the seed-derivation
# semantics (the HKDF labels below).  Bump on any change —
# ``tool/check_wire_format.py`` fingerprints it, so drift without a bump
# fails the build like any wire drift.
SECAGG_VERSION = 1

# Cumulative per-process secure-aggregation counters, surfaced beside
# ``fl.quorum.QUORUM_STATS``.  Defined HERE (the dependency-free end of
# the transport/fl split) and re-exported by ``rayfed_tpu.fl.secagg``;
# the transport side accounts ``keygen_ms``, the fl side the rest.
SECAGG_STATS: Dict[str, float] = {
    "masked_rounds": 0,
    "mask_recoveries": 0,
    "recovered_seeds": 0,
    "keygen_ms": 0.0,
}


class SecAggError(RuntimeError):
    """Secure-aggregation key agreement / masking failure."""


def _lp(*parts: bytes) -> bytes:
    """Length-prefixed concatenation: every component is framed by its
    own 4-byte big-endian length, so no two distinct component tuples
    share a preimage (a '|'-delimited scheme would let names containing
    the delimiter collide across pairs, handing one pair another pair's
    mask seed)."""
    out = []
    for p in parts:
        out.append(len(p).to_bytes(4, "big"))
        out.append(p)
    return b"".join(out)


def hkdf_sha256(ikm: bytes, info: bytes,
                salt: bytes = b"rayfed-secagg-v1", length: int = 32) -> bytes:
    """RFC 5869 HKDF-SHA256 (extract + one expand block), pure stdlib."""
    if not 1 <= length <= 32:
        raise ValueError("hkdf_sha256 emits at most one SHA-256 block")
    prk = hmac.new(salt, ikm, hashlib.sha256).digest()
    return hmac.new(prk, info + b"\x01", hashlib.sha256).digest()[:length]


def _default_prg_scheme() -> str:
    forced = os.environ.get("RAYFED_SECAGG_PRG")
    if forced:
        if forced not in ("aes", "philox"):
            raise SecAggError(
                f"RAYFED_SECAGG_PRG={forced!r} — expected 'aes' or 'philox'"
            )
        if forced == "aes" and not HAVE_AES:
            raise SecAggError(
                "RAYFED_SECAGG_PRG=aes but the 'cryptography' package is "
                "not installed (pip install 'rayfed-tpu[secagg]')"
            )
        return forced
    return "aes" if HAVE_AES else "philox"


class KeyAgreement:
    """Per-process (per-session) secure-aggregation key state.

    One instance per :class:`~rayfed_tpu.transport.manager.
    TransportManager` — NOT module-global, so several in-process parties
    (tests, benches) each hold their own keypair.  Thread-safe: peers
    are recorded from transport-loop threads (HELLO dispatch) and read
    from driver/aggregator threads.
    """

    def __init__(self, party: str, group_key: Optional[bytes] = None) -> None:
        self.party = str(party)
        t0 = time.perf_counter()
        if HAVE_X25519:
            self.kex_scheme = "x25519"
            self._priv = X25519PrivateKey.generate()
            self._pub = self._priv.public_key().public_bytes(
                Encoding.Raw, PublicFormat.Raw
            )
        else:
            # Stdlib fallback: a fresh per-session nonce.  The pair
            # secret then needs the operator-provisioned group key —
            # see the module docstring for what this mode protects.
            self.kex_scheme = "nonce"
            self._priv = None
            self._pub = os.urandom(32)
        SECAGG_STATS["keygen_ms"] += (time.perf_counter() - t0) * 1e3
        self.prg_scheme = _default_prg_scheme()
        if group_key is None:
            env = os.environ.get("RAYFED_SECAGG_GROUP_KEY")
            group_key = env.encode() if env else None
        self._group_key = group_key
        self._lock = threading.Lock()
        # party -> (kex_scheme, prg_scheme, public bytes)
        self._peers: Dict[str, Tuple[str, str, bytes]] = {}
        self._pair_secrets: Dict[str, bytes] = {}

    # -- HELLO advertisement ---------------------------------------------------

    def hello_value(self) -> str:
        """The value published under ``wire.SECAGG_PUB_KEY`` in every
        HELLO: ``"<version>.<kex>.<prg>.<hex public bytes>"`` — the
        single producer of the format ``tool/check_wire_format.py``
        fingerprints (via :data:`SECAGG_VERSION`)."""
        return (
            f"{SECAGG_VERSION}.{self.kex_scheme}.{self.prg_scheme}."
            f"{self._pub.hex()}"
        )

    def record_peer(self, party: str, value: str) -> None:
        """Record a peer's HELLO advertisement (loop threads).

        Malformed or future-version values are logged and ignored — key
        agreement is an opportunistic rider on the handshake; the loud
        failure belongs at mask time (:meth:`pair_secret`), where the
        missing state actually bites.  A re-advertisement (peer restart
        → fresh session keypair) replaces the old record and invalidates
        the cached pair secret.
        """
        party = str(party)
        if party == self.party:
            return
        try:
            ver_s, kex, prg, hexpub = str(value).split(".", 3)
            ver = int(ver_s)
            pub = bytes.fromhex(hexpub)
        except (ValueError, TypeError):
            logger.warning(
                "[%s] ignoring malformed secagg HELLO value from %s: %r",
                self.party, party, value,
            )
            return
        if ver > SECAGG_VERSION:
            logger.warning(
                "[%s] peer %s advertises secagg v%d; this party speaks "
                "up to v%d — ignoring its key (upgrade to compose "
                "secure aggregation with it)",
                self.party, party, ver, SECAGG_VERSION,
            )
            return
        if len(pub) != 32:
            logger.warning(
                "[%s] ignoring secagg key of %d bytes from %s",
                self.party, len(pub), party,
            )
            return
        with self._lock:
            prev = self._peers.get(party)
            self._peers[party] = (kex, prg, pub)
            if prev is not None and prev[2] != pub:
                # Fresh session on the peer's side: pair secrets derived
                # from the old keypair are dead.
                self._pair_secrets.pop(party, None)
                logger.info(
                    "[%s] peer %s re-advertised a new secagg key "
                    "(restarted session)", self.party, party,
                )

    def has_peer(self, party: str) -> bool:
        with self._lock:
            return party in self._peers

    def set_group_key(self, key: bytes) -> None:
        """Provision the shared group key for the ``nonce`` fallback
        (deployment policy, like TLS certs); invalidates cached pair
        secrets so a rekey takes effect immediately."""
        with self._lock:
            self._group_key = bytes(key)
            self._pair_secrets.clear()

    def describe(self) -> Dict[str, object]:
        """Key-agreement state for ``get_stats()``: this party's suite
        plus, per peer, the scheme its recorded key arrived under."""
        with self._lock:
            return {
                "kex": self.kex_scheme,
                "prg": self.prg_scheme,
                "peers": {
                    p: f"{kex}/{prg}"
                    for p, (kex, prg, _pub) in sorted(self._peers.items())
                },
            }

    # -- pair secrets / mask seeds --------------------------------------------

    def pair_secret(self, peer: str) -> bytes:
        """The (cached) 32-byte pair secret shared with ``peer``.

        Raises :class:`SecAggError` naming the exact gap — no recorded
        peer key, mismatched schemes, or a missing group key — instead
        of ever deriving masks that cannot cancel.
        """
        peer = str(peer)
        with self._lock:
            cached = self._pair_secrets.get(peer)
            if cached is not None:
                return cached
            state = self._peers.get(peer)
        if state is None:
            raise SecAggError(
                f"no secure-aggregation key recorded for peer {peer!r} — "
                f"it has not completed a HELLO handshake with this party "
                f"(TransportManager.ensure_secagg_peer_keys pings every "
                f"peer once to establish the pair)"
            )
        kex, prg, pub = state
        if kex != self.kex_scheme or prg != self.prg_scheme:
            raise SecAggError(
                f"secure-aggregation suite mismatch with {peer!r}: this "
                f"party runs {self.kex_scheme}/{self.prg_scheme}, the "
                f"peer advertises {kex}/{prg} — masks expanded from "
                f"different suites cannot cancel.  Align the installs "
                f"(pip install 'rayfed-tpu[secagg]' everywhere) or pin "
                f"RAYFED_SECAGG_PRG on every party"
            )
        lo, hi = sorted((self.party, peer))
        lo_b, hi_b = lo.encode(), hi.encode()
        if self.kex_scheme == "x25519":
            dh = self._priv.exchange(X25519PublicKey.from_public_bytes(pub))
            lo_pub, hi_pub = (
                (self._pub, pub) if lo == self.party else (pub, self._pub)
            )
            secret = hkdf_sha256(
                dh, _lp(b"pair-secret", lo_b, hi_b, lo_pub, hi_pub)
            )
        else:
            with self._lock:
                gk = self._group_key
            if gk is None:
                raise SecAggError(
                    "secure aggregation without the 'cryptography' "
                    "package needs an operator-provisioned group key "
                    "for the nonce fallback — set RAYFED_SECAGG_GROUP_KEY "
                    "or call KeyAgreement.set_group_key(); install "
                    "'rayfed-tpu[secagg]' for the X25519 exchange that "
                    "needs no shared secret"
                )
            lo_pub, hi_pub = (
                (self._pub, pub) if lo == self.party else (pub, self._pub)
            )
            secret = hkdf_sha256(
                gk, _lp(b"pair-secret-psk", lo_b, hi_b, lo_pub, hi_pub)
            )
        with self._lock:
            self._pair_secrets[peer] = secret
        return secret

    def pair_seed(self, peer: str, *, session: str, stream: str,
                  round_index: int) -> bytes:
        """The pair's 256-bit mask seed for ONE (session, stream, round).

        Symmetric — both endpoints derive the identical seed (the pair
        is canonicalized by sorted party name; the lower-named party
        ADDS the expanded keystream, the higher-named SUBTRACTS it, so
        each pair mask appears exactly once positive and once negative
        across the parties).  Scoped by session, stream AND round: a
        failover attempt re-keys (fresh stream scope), two runs in one
        process re-key (fresh session), and revealing one round's seed
        during dropout recovery reveals no other round's (HKDF is
        one-way in the pair secret).
        """
        lo, hi = sorted((self.party, str(peer)))
        info = _lp(
            b"mask-seed", lo.encode(), hi.encode(),
            str(session).encode(), str(stream).encode(),
            int(round_index).to_bytes(8, "big"),
        )
        return hkdf_sha256(self.pair_secret(peer), info)
