"""Cross-party DCN transport.

Replaces the reference's Ray-actor-hosted gRPC unary push transport
(``fed/barriers.py``, ``fed/grpc/fed.proto``) with an asyncio framed-TCP
transport designed for device arrays: a zero-copy tensor wire format
(:mod:`rayfed_tpu.transport.wire`), an either-side-first rendezvous mailbox
(:mod:`rayfed_tpu.transport.rendezvous`), persistent multiplexed
connections with retry policy (:mod:`rayfed_tpu.transport.client`), and an
in-process :class:`~rayfed_tpu.transport.manager.TransportManager` hosting
both proxies on one asyncio loop thread.
"""

from rayfed_tpu.transport.manager import TransportManager
from rayfed_tpu.transport.wire import encode_payload, decode_payload

__all__ = ["TransportManager", "encode_payload", "decode_payload"]
