"""Local-link fast path: colocation detection + same-process delivery.

The transport backend is a per-link decision (``local_link:
auto|uds|shm|off`` in transport options).  This module holds the three
pieces every backend upgrade needs:

- **Colocation proof.**  :func:`host_identity` is a boot-scoped host
  fingerprint (machine-id + boot-id hash) every server volunteers in its
  HELLO reply under :data:`wire.LOCAL_HOST_KEY`; two endpoints that
  present the same value share a kernel, so an AF_UNIX socket (path
  advertised under :data:`wire.LOCAL_UDS_KEY`) reaches the peer without
  the loopback-TCP stack.  :func:`process_token` goes one step further —
  a per-process random token under :data:`wire.LOCAL_TOKEN_KEY` proves
  the peer lives in THIS interpreter, unlocking the shared-memory
  handoff below.

- **In-process server registry.**  Virtual parties (benches, tests, the
  hierarchy ladder) run every :class:`TransportServer` in one process;
  :func:`register_server` / :func:`lookup_addr` let a client discover
  the destination server object without ever opening a probe socket —
  at N=64 that alone removes ~2k loopback connections per round.

- **Shared-memory handoff.**  :func:`deliver` hands a payload buffer to
  the destination server BY REFERENCE: the buffer is scheduled onto the
  server's event loop and pushed through ``_FrameProtocol``'s normal
  dispatch chain, so chunk sinks, epoch rejects, chaos ``wire``/
  ``server_frame`` hooks, receive stats, telemetry ``wire.deliver``
  spans, observers and the mailbox all behave exactly as on a socket.
  Per-chunk CRC is elided on this path — the bytes never leave the
  machine, and the handoff buffer is freshly allocated per send (the
  PR 5 ping-pong arenas stay OUT of this path: their slot reuse at
  round+2 would dangle under a zero-copy receiver holding the previous
  round's views).

Import discipline: ``server.py`` and ``client.py`` both import this
module at top level, so this module imports them only lazily inside
functions.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os
import secrets
import tempfile
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from rayfed_tpu.transport import wire

logger = logging.getLogger(__name__)

#: Valid values of the ``local_link`` transport option.
LINK_MODES = ("auto", "uds", "shm", "off")


# -- colocation identity ------------------------------------------------------

_HOST_ID: Optional[str] = None
# One random token per interpreter: presenting it back proves the HELLO
# reply was produced by THIS process (a pid alone recycles; a copied
# config file can't fake 128 random bits).
_PROCESS_TOKEN = f"{os.getpid():x}-{secrets.token_hex(16)}"


def host_identity() -> str:
    """Boot-scoped host fingerprint two colocated processes agree on.

    machine-id + boot-id hashed together: stable across processes on one
    running kernel, different across hosts AND across reboots of the
    same host (a stale advertisement can never alias a different boot's
    socket paths).  Hostname fallback for systems exposing neither.
    """
    global _HOST_ID
    if _HOST_ID is None:
        parts = []
        for path in ("/etc/machine-id", "/proc/sys/kernel/random/boot_id"):
            try:
                with open(path) as f:
                    parts.append(f.read().strip())
            except OSError:
                pass
        if not parts:
            import socket as _socket

            parts = [_socket.gethostname()]
        _HOST_ID = hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]
    return _HOST_ID


def process_token() -> str:
    return _PROCESS_TOKEN


def make_uds_path() -> str:
    """A fresh AF_UNIX path for one server's twin listener.

    Kept short on purpose: ``sun_path`` caps at ~104 bytes and a deep
    ``$TMPDIR`` must not silently truncate into a collision."""
    name = f"rfw-{os.getpid()}-{secrets.token_hex(4)}.sock"
    return os.path.join(tempfile.gettempdir(), name)


# -- in-process server registry ----------------------------------------------


class LocalEndpoint:
    """One registered in-process server: the object + its event loop."""

    __slots__ = ("server", "loop", "sid")

    def __init__(self, server: Any, loop: asyncio.AbstractEventLoop, sid: str):
        self.server = server
        self.loop = loop
        self.sid = sid


_REG_LOCK = threading.Lock()
_BY_ADDR: Dict[Tuple[str, int], LocalEndpoint] = {}
_BY_SID: Dict[str, LocalEndpoint] = {}
_SID_SEQ = 0

_LOOPBACK = frozenset({"", "0.0.0.0", "localhost", "127.0.0.1", "::", "::1"})


def _norm_host(host: str) -> str:
    return "127.0.0.1" if host in _LOOPBACK else host


def register_server(server: Any, loop: asyncio.AbstractEventLoop,
                    host: str, port: int) -> str:
    """Record a started server; returns its registry id (``sid``)."""
    global _SID_SEQ
    with _REG_LOCK:
        _SID_SEQ += 1
        sid = str(_SID_SEQ)
        ep = LocalEndpoint(server, loop, sid)
        _BY_ADDR[(_norm_host(host), int(port))] = ep
        _BY_SID[sid] = ep
        return sid


def unregister_server(sid: Optional[str]) -> None:
    if sid is None:
        return
    with _REG_LOCK:
        ep = _BY_SID.pop(sid, None)
        if ep is not None:
            for key, val in list(_BY_ADDR.items()):
                if val is ep:
                    del _BY_ADDR[key]


def lookup_addr(host: str, port: int) -> Optional[LocalEndpoint]:
    """The in-process server listening on ``host:port``, if any."""
    with _REG_LOCK:
        return _BY_ADDR.get((_norm_host(host), int(port)))


def endpoint_alive(ep: LocalEndpoint) -> bool:
    """Synchronous liveness verdict for an in-process peer: still
    registered (its manager hasn't stopped) and its loop still runs.

    This is what makes health monitoring O(1) on shm links: an
    in-process peer cannot die independently of this registry — no
    ping roundtrip needed, and GIL starvation under N virtual parties
    can never read as death (the false positive a wire ping deadline
    risks exactly when the process is busiest)."""
    with _REG_LOCK:
        live = _BY_SID.get(ep.sid) is ep
    return live and not ep.loop.is_closed()


def endpoint_token(sid: str) -> str:
    """The HELLO ``lt`` value naming one in-process server."""
    return f"{_PROCESS_TOKEN}:{sid}"


def lookup_token(token: Optional[str]) -> Optional[LocalEndpoint]:
    """Resolve a HELLO ``lt`` advertisement — None unless it names a
    live server in THIS process (the random-token prefix is the proof)."""
    if not token:
        return None
    ptok, _, sid = token.partition(":")
    if ptok != _PROCESS_TOKEN:
        return None
    with _REG_LOCK:
        return _BY_SID.get(sid)


# -- coalesced cross-loop scheduling ------------------------------------------


class _LoopBatcher:
    """Coalesce cross-thread callbacks onto one event loop.

    ``loop.call_soon_threadsafe`` writes the self-pipe wake byte on
    EVERY call; in an N=64 all-to-all burst that is ~3 wake syscalls
    per message and the flight recorder showed the wake path
    (``_write_to_self``) as the single largest non-idle cost of the
    hierarchy round.  The batcher arms the loop ONCE: callbacks posted
    while the drain is still pending ride the same wake for free, from
    any producer thread.  FIFO order is preserved (single queue, one
    drainer), so delivery/reply ordering is exactly the unbatched
    behaviour.

    A callback posted after the target loop died is dropped and the
    post raises ``RuntimeError`` only when it is the arming call — the
    same contract as ``call_soon_threadsafe`` itself, and the deliver
    path maps both outcomes to the socket analogue (refused connection
    at arm time, reply-deadline timeout for queued-but-undrained).
    """

    __slots__ = ("loop", "_lock", "_queue", "_armed")

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self.loop = loop
        self._lock = threading.Lock()
        self._queue: deque = deque()
        self._armed = False

    def post(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._queue.append(fn)
            if self._armed:
                return
            self._armed = True
        try:
            self.loop.call_soon_threadsafe(self._drain)
        except RuntimeError:
            with self._lock:
                self._armed = False
            raise

    def _drain(self) -> None:
        while True:
            with self._lock:
                if not self._queue:
                    self._armed = False
                    return
                fns = list(self._queue)
                self._queue.clear()
            for fn in fns:
                try:
                    fn()
                except Exception:  # pragma: no cover - callback bug
                    logger.exception("batched loop callback failed")


_BATCHERS: "weakref.WeakKeyDictionary[asyncio.AbstractEventLoop, _LoopBatcher]" = (
    weakref.WeakKeyDictionary()
)
_BATCHERS_LOCK = threading.Lock()


def loop_batcher(loop: asyncio.AbstractEventLoop) -> _LoopBatcher:
    """The (one) coalescing scheduler for ``loop``."""
    with _BATCHERS_LOCK:
        b = _BATCHERS.get(loop)
        if b is None:
            b = _LoopBatcher(loop)
            _BATCHERS[loop] = b
        return b


def post_coroutine(loop: asyncio.AbstractEventLoop, coro) -> "Future":
    """``asyncio.run_coroutine_threadsafe`` with a coalesced wake.

    Identical contract for the caller — a ``concurrent.futures.Future``
    resolving with the coroutine's result — but the loop is armed
    through :func:`loop_batcher`, so a burst of dispatches (the N-1
    sends of a hierarchy fan-out) costs one self-pipe wake instead of
    one per coroutine.  Cancelling the returned future does NOT cancel
    the task (no caller does; ``run_coroutine_threadsafe``'s two-way
    chain is the one piece not reproduced here).  Raises
    ``RuntimeError`` like ``call_soon_threadsafe`` if the loop is gone
    at arm time.
    """
    from concurrent.futures import Future

    cf: Future = Future()

    def _start() -> None:
        try:
            # fedlint: disable=FED002 — _start executes ON the loop thread: it only ever runs inside _LoopBatcher._drain, which the batcher schedules via call_soon_threadsafe
            task = loop.create_task(coro)
        except Exception as e:
            cf.set_exception(e)
            return

        def _chain(t: "asyncio.Task") -> None:
            if t.cancelled():
                cf.cancel()
                return
            exc = t.exception()
            if exc is not None:
                cf.set_exception(exc)
            else:
                cf.set_result(t.result())

        task.add_done_callback(_chain)

    loop_batcher(loop).post(_start)
    return cf


# -- shared-memory delivery ---------------------------------------------------

_DELIVERY_CLS = None


def _delivery_protocol_cls():
    """The one-shot delivery protocol (lazy: avoids a server import cycle).

    A ``_FrameProtocol`` with no transport: parse state is injected
    directly and ``_dispatch_frame`` runs unmodified, so every receive
    semantic — chaos hooks, CRC verify (including a chaos-corrupted
    declared CRC), epoch rejects, observers, chunk sinks, stats,
    telemetry — is the socket path's own code.  Replies are forwarded
    to the sender's loop instead of written to a transport.
    """
    global _DELIVERY_CLS
    if _DELIVERY_CLS is None:
        from rayfed_tpu.transport.server import _FrameProtocol

        class _ShmDelivery(_FrameProtocol):
            def __init__(self, server, on_reply):
                super().__init__(server)
                self._on_reply = on_reply

            def _reply(self, msg_type, header):
                self._on_reply(msg_type, header)

            def _abort(self):
                self._closed = True

        _DELIVERY_CLS = _ShmDelivery
    return _DELIVERY_CLS


def _map_remote_error(header: Dict[str, Any]) -> Exception:
    # Same classification as TransportClient._read_responses.
    from rayfed_tpu.transport.client import (
        DeltaBaseError, FatalSendError, ProtocolMismatchError, SendError,
    )

    if header.get("code") == "protocol":
        exc_cls: type = ProtocolMismatchError
    elif header.get("fatal"):
        exc_cls = FatalSendError
    elif header.get("code") == "delta_base":
        exc_cls = DeltaBaseError
    else:
        exc_cls = SendError
    return exc_cls(header.get("error", "remote error"))


async def deliver(
    endpoint: LocalEndpoint,
    msg_type: int,
    header: Dict[str, Any],
    payload,
    timeout_s: float,
) -> Dict[str, Any]:
    """Hand one frame to an in-process server and await its reply.

    Runs on the SENDER's event loop; the frame is marshaled onto the
    destination server's loop (they differ — every virtual party runs
    its own) and pushed through the normal dispatch chain.  The reply
    resolves a future back on the sender's loop.  Raises the same
    exception classes a socket roundtrip would: ``asyncio.TimeoutError``
    on a reply deadline (e.g. the receiver discarded the frame under a
    chaos fault — no ACK is the point), mapped ``SendError`` subclasses
    for MSG_ERR replies.
    """
    loop = asyncio.get_running_loop()
    fut: asyncio.Future = loop.create_future()
    reply_batcher = loop_batcher(loop)

    def _on_reply(reply_type: int, reply_header: Dict[str, Any]) -> None:
        def _resolve() -> None:
            if fut.done():
                return
            if reply_type == wire.MSG_ERR:
                fut.set_exception(_map_remote_error(reply_header))
            else:
                fut.set_result(reply_header)

        try:
            reply_batcher.post(_resolve)
        except RuntimeError:  # sender loop gone mid-shutdown: nobody waits
            pass

    proto = _delivery_protocol_cls()(endpoint.server, _on_reply)
    t_handoff = time.perf_counter()

    def _run() -> None:
        server = endpoint.server
        try:
            if (
                msg_type == wire.MSG_DATA
                and len(payload) > server._max_message_size
            ):
                # Mirror _fatal_oversize (the prefix-stage reject a
                # socket receiver would have issued).
                _on_reply(wire.MSG_ERR, {
                    "rid": header.get("rid"),
                    "fatal": True,
                    "error": f"message of {len(payload)} bytes exceeds "
                             f"max {server._max_message_size}",
                })
                return
            # Same liveness credit a socket read would earn: a party
            # actively handing us payload bytes is alive.
            server.note_rx_progress(header.get("src"), len(payload))
            # Inject parse state as if the frame was just read, then
            # dispatch through the unmodified receive chain.
            proto._msg_type = msg_type
            proto._flags = 0
            proto._header = header
            proto._plen = len(payload)
            proto._payload = payload
            proto._payload_view = None
            proto._payload_t0 = t_handoff
            proto._dispatch_frame()
        except Exception as e:  # pragma: no cover - dispatch bug
            logger.exception(
                "[%s] local delivery dispatch failed", server._party
            )
            _on_reply(wire.MSG_ERR, {
                "rid": header.get("rid"),
                "error": f"local delivery failed: {e}",
            })

    try:
        loop_batcher(endpoint.loop).post(_run)
    except RuntimeError as e:
        # The destination's event loop is gone (its manager shut down):
        # the socket-path analogue is a refused connection.
        from rayfed_tpu.transport.client import SendError

        raise SendError(
            f"local delivery failed: destination loop closed ({e})"
        ) from e
    return await asyncio.wait_for(fut, timeout=timeout_s)


def materialize(payload_bufs: List) -> Tuple[Any, float, float]:
    """Executor job: fetch + gather the payload into ONE fresh buffer.

    The result is handed to the receiver by reference, so it must be
    freshly allocated here (never a reused arena slot) — this gather is
    the single copy a shared-memory send pays.  Returns
    ``(buffer, d2h_seconds, copy_seconds)``.
    """
    t0 = time.perf_counter()
    views = []
    for buf in payload_bufs:
        host = buf.produce() if isinstance(buf, wire.LazyBuffer) else buf
        mv = host if isinstance(host, memoryview) else memoryview(host)
        if mv.format != "B":
            mv = mv.cast("B")
        views.append(mv)
    d2h_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    if len(views) == 1:
        payload: Any = bytearray(views[0])
    else:
        from rayfed_tpu import native

        payload = native.gather_copy(views)
    return payload, d2h_s, time.perf_counter() - t1
