"""ResNet (v1.5) in functional JAX — the cross-silo CV workload.

Covers BASELINE.md config #3 (4-party FedAvg ResNet-18 / CIFAR-10).
NHWC layout (TPU-native for convolutions), batch-norm running statistics
carried in an explicit ``state`` pytree (functionally pure — FedAvg can
average params and states alike), and a CIFAR-style stem option (3×3
conv, no max-pool) for 32×32 inputs.

Under ``jit`` with the batch sharded over ``dp``, the batch-norm
reductions are *global* means in the SPMD program — XLA inserts the
cross-device psums automatically, so multi-device BN is sync-BN for free.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]
State = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int] = (2, 2, 2, 2)  # ResNet-18
    num_classes: int = 10
    width: int = 64
    small_inputs: bool = True  # CIFAR stem: 3x3/1 conv, no maxpool
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5
    dtype: Any = jnp.float32


def resnet18(num_classes: int = 10, **kw) -> "ResNetConfig":
    return ResNetConfig(stage_sizes=(2, 2, 2, 2), num_classes=num_classes, **kw)


def resnet34(num_classes: int = 10, **kw) -> "ResNetConfig":
    return ResNetConfig(stage_sizes=(3, 4, 6, 3), num_classes=num_classes, **kw)


def _conv_init(key, kh, kw, c_in, c_out):
    fan_in = kh * kw * c_in
    return jax.random.normal(key, (kh, kw, c_in, c_out)) * (2.0 / fan_in) ** 0.5


def _bn_params(c):
    return {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}


def _bn_state(c):
    return {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))}


def init_resnet(
    key: jax.Array, config: ResNetConfig, input_channels: int = 3
) -> Tuple[Params, State]:
    params: Params = {}
    state: State = {}
    keys = iter(jax.random.split(key, 4 + 2 * sum(config.stage_sizes) * 3))

    stem_k = 3 if config.small_inputs else 7
    params["stem"] = {
        "conv": _conv_init(next(keys), stem_k, stem_k, input_channels, config.width),
        "bn": _bn_params(config.width),
    }
    state["stem"] = _bn_state(config.width)

    c_in = config.width
    for stage, num_blocks in enumerate(config.stage_sizes):
        c_out = config.width * (2**stage)
        for block in range(num_blocks):
            name = f"stage{stage}_block{block}"
            stride = 2 if (block == 0 and stage > 0) else 1
            bp: Params = {
                "conv1": _conv_init(next(keys), 3, 3, c_in, c_out),
                "bn1": _bn_params(c_out),
                "conv2": _conv_init(next(keys), 3, 3, c_out, c_out),
                "bn2": _bn_params(c_out),
            }
            bs: State = {"bn1": _bn_state(c_out), "bn2": _bn_state(c_out)}
            if stride != 1 or c_in != c_out:
                bp["proj"] = _conv_init(next(keys), 1, 1, c_in, c_out)
                bp["proj_bn"] = _bn_params(c_out)
                bs["proj_bn"] = _bn_state(c_out)
            params[name] = bp
            state[name] = bs
            c_in = c_out

    params["head"] = {
        "kernel": jnp.zeros((c_in, config.num_classes)),
        "bias": jnp.zeros((config.num_classes,)),
    }
    return params, state


def _conv(x, kernel, stride=1):
    return jax.lax.conv_general_dilated(
        x,
        kernel.astype(x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _batch_norm(x, p, s, *, train: bool, momentum: float, eps: float):
    if train:
        mean = jnp.mean(x, axis=(0, 1, 2))
        var = jnp.var(x, axis=(0, 1, 2))
        new_s = {
            "mean": momentum * s["mean"] + (1 - momentum) * mean,
            "var": momentum * s["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = jax.lax.rsqrt(var + eps) * p["scale"]
    out = (x - mean.astype(x.dtype)) * inv.astype(x.dtype) + p["bias"].astype(x.dtype)
    return out, new_s


def apply_resnet(
    params: Params,
    state: State,
    x: jax.Array,
    config: ResNetConfig,
    *,
    train: bool = False,
) -> Tuple[jax.Array, State]:
    """Forward pass: NHWC images → logits.  Returns updated BN state."""
    new_state: State = {}
    x = x.astype(config.dtype)

    stem_stride = 1 if config.small_inputs else 2
    x = _conv(x, params["stem"]["conv"], stride=stem_stride)
    x, new_state["stem"] = _batch_norm(
        x,
        params["stem"]["bn"],
        state["stem"],
        train=train,
        momentum=config.bn_momentum,
        eps=config.bn_eps,
    )
    x = jax.nn.relu(x)
    if not config.small_inputs:
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
        )

    for stage, num_blocks in enumerate(config.stage_sizes):
        for block in range(num_blocks):
            name = f"stage{stage}_block{block}"
            bp, bs = params[name], state[name]
            nbs: State = {}
            stride = 2 if (block == 0 and stage > 0) else 1

            residual = x
            y = _conv(x, bp["conv1"], stride=stride)
            y, nbs["bn1"] = _batch_norm(
                y, bp["bn1"], bs["bn1"], train=train,
                momentum=config.bn_momentum, eps=config.bn_eps,
            )
            y = jax.nn.relu(y)
            y = _conv(y, bp["conv2"])
            y, nbs["bn2"] = _batch_norm(
                y, bp["bn2"], bs["bn2"], train=train,
                momentum=config.bn_momentum, eps=config.bn_eps,
            )
            if "proj" in bp:
                residual = _conv(x, bp["proj"], stride=stride)
                residual, nbs["proj_bn"] = _batch_norm(
                    residual, bp["proj_bn"], bs["proj_bn"], train=train,
                    momentum=config.bn_momentum, eps=config.bn_eps,
                )
            x = jax.nn.relu(y + residual)
            new_state[name] = nbs

    x = jnp.mean(x, axis=(1, 2))  # global average pool
    logits = x @ params["head"]["kernel"].astype(x.dtype) + params["head"]["bias"]
    return logits.astype(jnp.float32), new_state


# FSDP/TP partitioning rules for shard_params_by_rules: conv kernels shard
# output channels (last dim) over fsdp; the head over tp if present.
PARTITION_RULES = (
    (r"conv|proj$", P(None, None, None, "fsdp")),
    (r"head/kernel", P(None, ("fsdp", "tp"))),
)


def _make_loss_fn(config: ResNetConfig):
    from rayfed_tpu.models.logistic import softmax_cross_entropy

    def loss_fn(params, state, x, y):
        logits, new_state = apply_resnet(params, state, x, config, train=True)
        return softmax_cross_entropy(logits, y), new_state

    return loss_fn


def _make_sgd_step(config: ResNetConfig, lr: float, momentum: float):
    """Shared un-jitted step body for both train-step factories — a
    change to the loss/update rule applies to the plain and fed paths
    alike."""
    loss_fn = _make_loss_fn(config)

    def step(params, state, opt, x, y):
        (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, state, x, y
        )
        new_opt = jax.tree_util.tree_map(
            lambda m, g: momentum * m + g, opt, grads
        )
        new_params = jax.tree_util.tree_map(
            lambda p, m: p - lr * m, params, new_opt
        )
        return new_params, new_state, new_opt, loss

    return step


def make_train_step(
    config: ResNetConfig,
    lr: float = 0.1,
    momentum: float = 0.9,
    *,
    donate: bool = False,
):
    """SGD-with-momentum train step: (params, state, opt, x, y) → (...).

    ``donate`` is opt-in: in a FedAvg flow the incoming params/state are
    also serialized for cross-party pushes, and donation would delete
    those buffers out from under the transport.  Donate only in
    single-owner training loops.
    """
    step = _make_sgd_step(config, lr, momentum)
    return jax.jit(step, donate_argnums=(0, 1, 2) if donate else ())


def init_opt_state(params: Params) -> Params:
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def make_fed_train_step(
    config: ResNetConfig,
    lr: float = 0.1,
    momentum: float = 0.9,
    *,
    wire_dtype: Any = jnp.bfloat16,
    local_steps: int = 1,
):
    """One FedAvg round's local work as a SINGLE jitted call.

    ``(wire_bundle, x, y) -> (wire_bundle, loss)`` where ``wire_bundle``
    is the ``(params, state)`` tree in ``wire_dtype`` exactly as it
    crosses parties (:mod:`rayfed_tpu.fl.compression` form) — EITHER the
    per-leaf tree OR the packed single-buffer form
    (:class:`~rayfed_tpu.fl.PackedTree`); the step returns the same form
    it was given.  The decompress (wire→f32), fresh-momentum init,
    ``local_steps`` SGD steps, and recompress (f32→wire) all live INSIDE
    the jit, so XLA fuses the casts into adjacent ops instead of the
    caller paying ~2×|params| of separate elementwise passes plus
    per-leaf dispatch per round — the difference matters when a round is
    seconds, not minutes (BASELINE.md #3's ≥0.9-of-floor target).  With
    a packed bundle the whole model additionally enters and leaves the
    step as ONE buffer — the form the wire pushes zero-copy.
    """
    from rayfed_tpu.fl.compression import (
        PackedTree,
        cast_floats,
        pack_tree,
        unpack_tree,
    )

    step = _make_sgd_step(config, lr, momentum)

    def fed_step(wire_bundle, x, y):
        packed = isinstance(wire_bundle, PackedTree)
        params, state = (
            unpack_tree(wire_bundle, jnp.float32)
            if packed
            else cast_floats(wire_bundle, jnp.float32)
        )
        opt = init_opt_state(params)
        loss = jnp.zeros((), jnp.float32)
        for _ in range(local_steps):
            params, state, opt, loss = step(params, state, opt, x, y)
        out = (
            pack_tree((params, state), wire_dtype)
            if packed
            else cast_floats((params, state), wire_dtype)
        )
        return out, loss

    return jax.jit(fed_step)
