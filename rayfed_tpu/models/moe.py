"""Mixture-of-Experts layer with expert parallelism over the ``ep`` axis.

Completes the SURVEY §2.10 parallelism checklist (DP/FSDP/TP/SP/PP/
ring/Ulysses live elsewhere; EP lives here).  TPU-first design: experts
are a stacked weight tensor ``[E, d, f]`` sharded over ``ep`` on dim 0;
routing uses dense top-k with a capacity factor so every shape is static
(XLA-friendly — no data-dependent gathers), and token dispatch/combine
are einsums against a one-hot dispatch mask, which XLA lowers to
all-to-alls when tokens and experts live on different mesh axes.

Gating: top-k softmax gating with auxiliary load-balancing loss
(Switch/GShard style).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    d_model: int = 64
    d_ff: int = 256
    aux_loss_weight: float = 0.01


def init_moe(key: jax.Array, config: MoeConfig) -> Params:
    e, d, f = config.num_experts, config.d_model, config.d_ff
    k_gate, k_in, k_out = jax.random.split(key, 3)
    return {
        "gate": jax.random.normal(k_gate, (d, e)) * d**-0.5,
        "w_in": jax.random.normal(k_in, (e, d, f)) * d**-0.5,
        "w_out": jax.random.normal(k_out, (e, f, d)) * f**-0.5,
    }


# Experts shard over ep; inner dims over tp when present.
PARTITION_RULES = (
    (r"w_(in|out)$", P("ep", None, "tp")),
    (r"gate$", P(None, None)),
)


# Above this many elements, the einsum path's [B,T,k,E,C] one-hot mask is
# a memory/FLOP blowup (tens of GB at T=8192, E=64) — refuse it and point
# at the scatter path, which is the default.
_EINSUM_DISPATCH_MAX_ELEMENTS = 1 << 30


def _route(params: Params, x: jax.Array, config: MoeConfig):
    """Shared top-k routing: gate values, expert ids, capacity ranks."""
    b, t, _ = x.shape
    e, k = config.num_experts, config.top_k

    logits = x @ params["gate"].astype(x.dtype)  # [B, T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, k)  # [B, T, k]
    # Renormalize over the selected k (GShard/Mixtral convention) so the
    # combine weights sum to 1 per token regardless of how much mass the
    # softmax put outside the top-k.
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(axis=-1, keepdims=True), 1e-9
    )

    # Position of each (token, choice) within its expert's capacity
    # buffer: 0-based rank in (t, k)-lexicographic priority order —
    # equivalent to a stable sort of assignments by expert id.
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)  # [B, T, k, E]
    flat = onehot.reshape(b, t * k, e)
    pos_in_expert = jnp.cumsum(flat, axis=1) * flat  # 1-based rank
    pos_in_expert = pos_in_expert.reshape(b, t, k, e) - 1
    return probs, gate_vals, expert_idx, onehot, pos_in_expert


def _expert_ffn(params: Params, expert_in: jax.Array) -> jax.Array:
    """[B, E, C, d] → [B, E, C, d]; E is a batched matmul dim on the MXU."""
    h = jax.nn.gelu(
        jnp.einsum(
            "becd,edf->becf", expert_in, params["w_in"].astype(expert_in.dtype)
        )
    )
    return jnp.einsum(
        "becf,efd->becd", h, params["w_out"].astype(expert_in.dtype)
    )


def apply_moe(
    params: Params,
    x: jax.Array,
    config: MoeConfig,
    *,
    return_aux: bool = False,
    dispatch: str = "scatter",
):
    """[B, T, d] → [B, T, d] with top-k expert routing.

    Static-shape dispatch: every expert processes a fixed capacity
    ``C = ceil(k·T·cf / E)`` tokens per batch row; overflow tokens are
    dropped (standard Switch behavior) and their output falls back to 0
    for that expert slot (residual connections outside absorb this).

    ``dispatch``:

    - ``"scatter"`` (default): rank-sorted sparse dispatch — tokens
      scatter into ``[B, E, C, d]`` expert buffers (out-of-capacity
      assignments drop in the scatter itself) and combine is a gather.
      O(B·T·k·d) routing work; peak routing memory is the buffers.
    - ``"einsum"``: the GShard-style one-hot ``[B, T, k, E, C]`` mask
      einsum.  O(B·T·E·C·d) dispatch FLOPs and a mask that reaches tens
      of GB at production shapes (T=8192, E=64) — kept as the reference
      implementation for numerics tests at small shapes; guarded above
      ``_EINSUM_DISPATCH_MAX_ELEMENTS``.

    Both paths share routing, so they agree exactly (tested in
    ``tests/test_moe.py``).
    """
    b, t, d = x.shape
    e, k = config.num_experts, config.top_k
    capacity = max(1, math.ceil(config.capacity_factor * k * t / e))

    probs, gate_vals, expert_idx, onehot, pos_in_expert = _route(
        params, x, config
    )
    keep = (pos_in_expert >= 0) & (pos_in_expert < capacity)

    if dispatch == "scatter":
        # Per-assignment expert rank: [B, T, k] (rank under ITS expert).
        pos_assign = jnp.max(pos_in_expert * onehot, axis=-1)
        bidx = jnp.arange(b)[:, None, None]  # [B, 1, 1] broadcasts to [B,T,k]
        # Scatter tokens into capacity buffers; ranks >= C fall outside
        # the buffer and XLA's "drop" mode discards them — the capacity
        # discipline costs no mask tensor at all.
        expert_in = jnp.zeros((b, e, capacity, d), x.dtype)
        x_rep = jnp.broadcast_to(x[:, :, None, :], (b, t, k, d))
        expert_in = expert_in.at[bidx, expert_idx, pos_assign].add(
            x_rep, mode="drop"
        )
        expert_out = _expert_ffn(params, expert_in)
        # Combine: gather each assignment's output back (dropped ranks
        # gather fill=0), weight by its gate value, sum over k.
        gathered = expert_out.at[bidx, expert_idx, pos_assign].get(
            mode="fill", fill_value=0
        )  # [B, T, k, d]
        out = jnp.sum(gathered * gate_vals[..., None].astype(x.dtype), axis=2)
    elif dispatch == "einsum":
        mask_elements = b * t * k * e * capacity
        if mask_elements > _EINSUM_DISPATCH_MAX_ELEMENTS:
            raise ValueError(
                f"einsum dispatch mask would hold {mask_elements} elements "
                f"([B={b}, T={t}, k={k}, E={e}, C={capacity}]); use "
                f'dispatch="scatter" at this scale'
            )
        # Dispatch mask [B, T, k, E, C] — one-hot over capacity slots.
        pos_clamped = jnp.clip(pos_in_expert, 0, capacity - 1)
        dispatch_mask = (
            jax.nn.one_hot(pos_clamped, capacity, dtype=x.dtype)
            * keep[..., None].astype(x.dtype)
            * onehot[..., None].astype(x.dtype)
        )  # [B, T, k, E, C]
        dispatch_tok = dispatch_mask.sum(axis=2)  # [B, T, E, C]
        combine = (
            dispatch_mask * gate_vals[..., None, None].astype(x.dtype)
        ).sum(axis=2)  # [B, T, E, C]
        expert_in = jnp.einsum("btec,btd->becd", dispatch_tok, x)
        expert_out = _expert_ffn(params, expert_in)
        out = jnp.einsum("btec,becd->btd", combine, expert_out)
    else:
        raise ValueError(f"unknown dispatch mode {dispatch!r}")

    if not return_aux:
        return out
    # Load-balancing auxiliary loss (Switch eq. 4): E * sum_e f_e * P_e.
    top1 = expert_idx[..., 0]
    frac_tokens = jnp.mean(
        jax.nn.one_hot(top1, e, dtype=jnp.float32), axis=(0, 1)
    )
    frac_probs = jnp.mean(probs, axis=(0, 1))
    aux = config.aux_loss_weight * e * jnp.sum(frac_tokens * frac_probs)
    return out, {
        "aux_loss": aux,
        "dropped_fraction": 1.0
        - jnp.mean(keep.any(axis=-1).astype(jnp.float32)),
    }
