"""Mixture-of-Experts layer with expert parallelism over the ``ep`` axis.

Completes the SURVEY §2.10 parallelism checklist (DP/FSDP/TP/SP/PP/
ring/Ulysses live elsewhere; EP lives here).  TPU-first design: experts
are a stacked weight tensor ``[E, d, f]`` sharded over ``ep`` on dim 0;
routing uses dense top-k with a capacity factor so every shape is static
(XLA-friendly — no data-dependent gathers), and token dispatch/combine
are einsums against a one-hot dispatch mask, which XLA lowers to
all-to-alls when tokens and experts live on different mesh axes.

Gating: top-k softmax gating with auxiliary load-balancing loss
(Switch/GShard style).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    d_model: int = 64
    d_ff: int = 256
    aux_loss_weight: float = 0.01


def init_moe(key: jax.Array, config: MoeConfig) -> Params:
    e, d, f = config.num_experts, config.d_model, config.d_ff
    k_gate, k_in, k_out = jax.random.split(key, 3)
    return {
        "gate": jax.random.normal(k_gate, (d, e)) * d**-0.5,
        "w_in": jax.random.normal(k_in, (e, d, f)) * d**-0.5,
        "w_out": jax.random.normal(k_out, (e, f, d)) * f**-0.5,
    }


# Experts shard over ep; inner dims over tp when present.
PARTITION_RULES = (
    (r"w_(in|out)$", P("ep", None, "tp")),
    (r"gate$", P(None, None)),
)


def apply_moe(
    params: Params,
    x: jax.Array,
    config: MoeConfig,
    *,
    return_aux: bool = False,
):
    """[B, T, d] → [B, T, d] with top-k expert routing.

    Static-shape dispatch: every expert processes a fixed capacity
    ``C = ceil(k·T·cf / E)`` tokens per batch row; overflow tokens are
    dropped (standard Switch behavior) and their output falls back to 0
    for that expert slot (residual connections outside absorb this).
    """
    b, t, d = x.shape
    e, k = config.num_experts, config.top_k
    capacity = max(1, math.ceil(config.capacity_factor * k * t / e))

    logits = x @ params["gate"].astype(x.dtype)  # [B, T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, k)  # [B, T, k]
    # Renormalize over the selected k (GShard/Mixtral convention) so the
    # combine weights sum to 1 per token regardless of how much mass the
    # softmax put outside the top-k.
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(axis=-1, keepdims=True), 1e-9
    )

    # Position of each (token, choice) within its expert's capacity buffer.
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)  # [B, T, k, E]
    flat = onehot.reshape(b, t * k, e)
    pos_in_expert = jnp.cumsum(flat, axis=1) * flat  # 1-based rank
    pos_in_expert = pos_in_expert.reshape(b, t, k, e) - 1
    keep = (pos_in_expert >= 0) & (pos_in_expert < capacity)

    # Dispatch mask [B, T, k, E, C] — one-hot over capacity slots.
    pos_clamped = jnp.clip(pos_in_expert, 0, capacity - 1)
    dispatch = (
        jax.nn.one_hot(pos_clamped, capacity, dtype=x.dtype)
        * keep[..., None].astype(x.dtype)
        * onehot[..., None].astype(x.dtype)
    )  # [B, T, k, E, C]
    dispatch_tok = dispatch.sum(axis=2)  # [B, T, E, C]
    combine = (
        dispatch * gate_vals[..., None, None].astype(x.dtype)
    ).sum(axis=2)  # [B, T, E, C]

    # Route tokens to expert buffers: [B, E, C, d].
    expert_in = jnp.einsum("btec,btd->becd", dispatch_tok, x)
    # Expert FFN (stacked weights; E is a batched matmul dim on the MXU).
    h = jax.nn.gelu(
        jnp.einsum("becd,edf->becf", expert_in, params["w_in"].astype(x.dtype))
    )
    expert_out = jnp.einsum("becf,efd->becd", h, params["w_out"].astype(x.dtype))
    # Combine back, weighted by gate values.
    out = jnp.einsum("btec,becd->btd", combine, expert_out)

    if not return_aux:
        return out
    # Load-balancing auxiliary loss (Switch eq. 4): E * sum_e f_e * P_e.
    top1 = expert_idx[..., 0]
    frac_tokens = jnp.mean(
        jax.nn.one_hot(top1, e, dtype=jnp.float32), axis=(0, 1)
    )
    frac_probs = jnp.mean(probs, axis=(0, 1))
    aux = config.aux_loss_weight * e * jnp.sum(frac_tokens * frac_probs)
    return out, {
        "aux_loss": aux,
        "dropped_fraction": 1.0
        - jnp.mean(keep.any(axis=-1).astype(jnp.float32)),
    }
