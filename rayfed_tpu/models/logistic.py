"""Logistic regression + small MLP — the horizontal-FL baseline models.

Covers BASELINE.md config #2 (2-party FedAvg on MNIST logistic
regression).  Kept deliberately simple: params are flat dicts, the train
step is one fused jit (forward + backward + SGD update), and the batch
dim shards over ``dp`` so the same code runs 1-device or across a mesh.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def init_logistic(key: jax.Array, num_features: int, num_classes: int) -> Params:
    return {
        "w": jnp.zeros((num_features, num_classes), jnp.float32),
        "b": jnp.zeros((num_classes,), jnp.float32),
    }


def apply_logistic(params: Params, x: jax.Array) -> jax.Array:
    return x @ params["w"] + params["b"]


def init_mlp(
    key: jax.Array, num_features: int, hidden: Tuple[int, ...], num_classes: int
) -> Params:
    dims = (num_features,) + tuple(hidden) + (num_classes,)
    params: Params = {}
    for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
        key, sub = jax.random.split(key)
        params[f"layer{i}"] = {
            "kernel": jax.random.normal(sub, (d_in, d_out)) * (2.0 / d_in) ** 0.5,
            "bias": jnp.zeros((d_out,)),
        }
    return params


def apply_mlp(params: Params, x: jax.Array) -> jax.Array:
    n = len(params)
    for i in range(n):
        layer = params[f"layer{i}"]
        x = x @ layer["kernel"] + layer["bias"]
        if i < n - 1:
            x = jax.nn.relu(x)
    return x


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean cross-entropy; ``labels`` are int class ids."""
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))


def make_train_step(apply_fn, lr: float = 0.1, *, donate: bool = False):
    """Fused SGD train step: (params, x, y) -> (params, loss).

    ``donate`` is opt-in: in a federated flow the incoming params are
    usually also being serialized for cross-party pushes (the same value
    goes to every party's trainer), and donation would delete those
    buffers out from under the transport.  Donate only when the caller
    owns the params exclusively (single-party training loops).
    """

    def loss_fn(params, x, y):
        return softmax_cross_entropy(apply_fn(params, x), y)

    def step(params, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    return jax.jit(step, donate_argnums=(0,) if donate else ())
