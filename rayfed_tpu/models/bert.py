"""BERT-style transformer encoder — the split/vertical-FL workload.

Covers BASELINE.md config #5 (encoder@alice → head@bob: alice runs the
encoder and pushes pooled activations across the silo boundary; bob runs
the classification head and pushes gradients back).  The module is
therefore explicitly split-friendly: :func:`apply_encoder` and
:func:`apply_head` are separate functions over separate param subtrees
(``split_params``), either side jit-compiles its half independently.

Post-LN BERT with learned positions; attention is pluggable (dense /
pallas flash / ring / Ulysses via ``attn_fn``).  TP partition rules shard
attention heads and the FFN intermediate over ``tp``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from rayfed_tpu.ops.attention import dot_product_attention

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 256
    num_layers: int = 4
    num_heads: int = 4
    intermediate_size: int = 1024
    max_position: int = 512
    num_classes: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.float32


def bert_base(**kw) -> BertConfig:
    return BertConfig(
        hidden_size=768, num_layers=12, num_heads=12, intermediate_size=3072, **kw
    )


def _dense_init(key, d_in, d_out, scale=0.02):
    return jax.random.normal(key, (d_in, d_out)) * scale


def _ln_params(d):
    return {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))}


def init_bert(key: jax.Array, config: BertConfig) -> Params:
    d, f = config.hidden_size, config.intermediate_size
    keys = iter(jax.random.split(key, 4 + 8 * config.num_layers))
    params: Params = {
        "embeddings": {
            "word": _dense_init(next(keys), config.vocab_size, d),
            "position": _dense_init(next(keys), config.max_position, d),
            "ln": _ln_params(d),
        }
    }
    for i in range(config.num_layers):
        params[f"layer{i}"] = {
            "attn": {
                "wq": _dense_init(next(keys), d, d),
                "wk": _dense_init(next(keys), d, d),
                "wv": _dense_init(next(keys), d, d),
                "wo": _dense_init(next(keys), d, d),
                "bq": jnp.zeros((d,)),
                "bk": jnp.zeros((d,)),
                "bv": jnp.zeros((d,)),
                "bo": jnp.zeros((d,)),
            },
            "ln1": _ln_params(d),
            "mlp": {
                "wi": _dense_init(next(keys), d, f),
                "bi": jnp.zeros((f,)),
                "wo": _dense_init(next(keys), f, d),
                "bo": jnp.zeros((d,)),
            },
            "ln2": _ln_params(d),
        }
    params["pooler"] = {
        "kernel": _dense_init(next(keys), d, d),
        "bias": jnp.zeros((d,)),
    }
    params["head"] = {
        "kernel": _dense_init(next(keys), d, config.num_classes),
        "bias": jnp.zeros((config.num_classes,)),
    }
    return params


def _layer_norm(x, p, eps):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    return out * p["scale"].astype(x.dtype) + p["bias"].astype(x.dtype)


def apply_encoder(
    params: Params,
    input_ids: jax.Array,
    config: BertConfig,
    *,
    attention_mask: Optional[jax.Array] = None,
    attn_fn: Callable = dot_product_attention,
) -> jax.Array:
    """Encoder: [B, T] token ids → [B, T, D] contextual embeddings."""
    b, t = input_ids.shape
    d = config.hidden_size
    h = config.num_heads
    emb = params["embeddings"]
    x = emb["word"].astype(config.dtype)[input_ids]
    x = x + emb["position"].astype(config.dtype)[None, :t, :]
    x = _layer_norm(x, emb["ln"], config.layer_norm_eps)

    mask = None
    if attention_mask is not None:
        mask = attention_mask[:, None, None, :].astype(bool)  # [B,1,1,T]

    for i in range(config.num_layers):
        layer = params[f"layer{i}"]
        a = layer["attn"]

        def proj(w, bias):
            return (x @ w.astype(x.dtype) + bias.astype(x.dtype)).reshape(b, t, h, -1)

        q, k, v = proj(a["wq"], a["bq"]), proj(a["wk"], a["bk"]), proj(a["wv"], a["bv"])
        if mask is not None:
            attn = attn_fn(q, k, v, mask=mask)
        else:
            attn = attn_fn(q, k, v)
        attn = attn.reshape(b, t, d) @ a["wo"].astype(x.dtype) + a["bo"].astype(x.dtype)
        x = _layer_norm(x + attn, layer["ln1"], config.layer_norm_eps)

        m = layer["mlp"]
        y = jax.nn.gelu(x @ m["wi"].astype(x.dtype) + m["bi"].astype(x.dtype))
        y = y @ m["wo"].astype(x.dtype) + m["bo"].astype(x.dtype)
        x = _layer_norm(x + y, layer["ln2"], config.layer_norm_eps)
    return x


def apply_pooler(params: Params, hidden: jax.Array) -> jax.Array:
    """[B, T, D] → [B, D]: tanh-projected [CLS] (position 0) embedding."""
    p = params["pooler"]
    return jnp.tanh(hidden[:, 0, :] @ p["kernel"].astype(hidden.dtype) + p["bias"])


def apply_head(params: Params, pooled: jax.Array) -> jax.Array:
    """Classification head over pooled activations: [B, D] → [B, C]."""
    p = params["head"]
    return (pooled @ p["kernel"].astype(pooled.dtype) + p["bias"]).astype(jnp.float32)


def apply_bert(
    params: Params,
    input_ids: jax.Array,
    config: BertConfig,
    *,
    attention_mask: Optional[jax.Array] = None,
    attn_fn: Callable = dot_product_attention,
) -> jax.Array:
    """Full model: ids → logits (encoder + pooler + head in one party)."""
    hidden = apply_encoder(
        params, input_ids, config, attention_mask=attention_mask, attn_fn=attn_fn
    )
    return apply_head(params, apply_pooler(params, hidden))


def split_params(params: Params) -> Tuple[Params, Params]:
    """Partition params for split FL: (encoder side, head side).

    Encoder side keeps embeddings + layers + pooler (alice); head side is
    the classifier (bob).  Keys are disjoint so FedAvg/optimizers can run
    per side.
    """
    encoder = {k: v for k, v in params.items() if k != "head"}
    head = {"head": params["head"]}
    return encoder, head


# TP rules: attention projections shard heads (output dim) over tp; FFN
# in over tp, out back over None; embeddings shard vocab over fsdp.
PARTITION_RULES = (
    (r"attn/w[qkv]", P(None, "tp")),
    (r"attn/wo", P("tp", None)),
    (r"mlp/wi", P(None, "tp")),
    (r"mlp/wo", P("tp", None)),
    (r"embeddings/word", P("fsdp", None)),
    (r"pooler/kernel|head/kernel", P(None, None)),
)
