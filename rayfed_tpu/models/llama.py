"""Llama-3-style decoder (RMSNorm, RoPE, GQA, SwiGLU) — the LLM workload.

Covers BASELINE.md config #4 (cross-silo Llama LoRA federated
fine-tune).  TPU-first design decisions:

- **Stacked layer params + ``lax.scan``**: all layers live in one pytree
  with a leading layer dim, the forward scans over it — one compiled
  layer body regardless of depth (fast compiles, natural pipeline
  stages), optionally rematerialized (``remat=True``) to trade FLOPs for
  HBM.
- **Pluggable attention**: dense, pallas flash, ring (sp axis) or
  Ulysses drop in via ``attn_fn`` — long-context sequence parallelism is
  a constructor argument, not a model rewrite.
- **bfloat16 activations** with float32 RMSNorm/softmax/logits.
- **LoRA as a low-rank bypass** (``x@A@B`` added to ``x@W``), never
  materializing ``W + AB`` — see :mod:`rayfed_tpu.models.lora`.

TP/FSDP partition rules shard attention heads and FFN width over ``tp``
and the remaining big dims over ``fsdp``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from rayfed_tpu.ops.attention import NEG_INF, dot_product_attention

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    intermediate_size: int = 14336
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    max_seq_len: int = 8192
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    # Storage dtype of the params.  float32 master weights are the
    # default; bfloat16 halves the param+grad HBM footprint (what lets a
    # ~1B-param model + Adam fit a single 16 GB v5e chip) at the cost of
    # rounding away updates below ~0.2% of a weight's magnitude.  Adam's
    # first moment follows this dtype; the second moment is always
    # float32 (see init_adam for why).
    param_dtype: Any = jnp.float32
    remat: bool = False
    # Rematerialization policy for the scanned layer body: None =
    # recompute everything (lowest memory); "dots" = keep matmul outputs
    # with no batch dims resident (jax.checkpoint_policies.
    # dots_with_no_batch_dims_saveable) — ~5% higher MFU when the
    # activations fit (v5e 1B bench: 0.522 -> 0.566 at b=2 seq=2048).
    remat_policy: Optional[str] = None
    # int8 KV cache (per-position-per-head symmetric scales over the
    # head dim): halves the cache's HBM footprint AND the per-token
    # cache traffic of the decode step — the long-context serving lever
    # (at T≈2048 the bf16 cache reads rival the weight reads).  The
    # scales fold into the score/probability tensors, so the cache is
    # read as raw int8 (see make_decode_step).
    kv_quant: bool = False
    # Sliding-window attention (Mistral style): each query sees only its
    # last `sliding_window` keys.  Applied uniformly by the training
    # forward, prefill, AND the decode step's cache mask; with the flash
    # attn_fn the out-of-band kv blocks are skipped in the kernel grid
    # (O(T·W) FLOPs).
    sliding_window: Optional[int] = None

    def __post_init__(self):
        if self.sliding_window is not None and self.sliding_window < 1:
            raise ValueError(
                f"sliding_window must be >= 1, got {self.sliding_window}"
            )
        if self.remat_policy not in (None, "dots"):
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r} "
                f"(expected None or 'dots')"
            )
        if self.remat_policy is not None and not self.remat:
            raise ValueError(
                "remat_policy is set but remat=False — the policy would "
                "silently never apply; enable remat or drop the policy"
            )

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def llama3_8b(**kw) -> LlamaConfig:
    return LlamaConfig(**kw)


def llama_tiny(**kw) -> LlamaConfig:
    """Test-scale config (runs on the CPU mesh in seconds)."""
    defaults = dict(
        vocab_size=256,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        intermediate_size=128,
        max_seq_len=128,
        dtype=jnp.float32,
    )
    defaults.update(kw)
    return LlamaConfig(**defaults)


def init_llama(key: jax.Array, config: LlamaConfig) -> Params:
    d = config.hidden_size
    dh = config.head_dim
    h, kv = config.num_heads, config.num_kv_heads
    f = config.intermediate_size
    L = config.num_layers
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    pdt = config.param_dtype

    def dense(key, *shape, fan_in):
        return (jax.random.normal(key, shape) * fan_in**-0.5).astype(pdt)

    lk = jax.random.split(k_layers, 7)
    params: Params = {
        "embed": (
            jax.random.normal(k_embed, (config.vocab_size, d)) * 0.02 * d**0.5
        ).astype(pdt),
        "layers": {
            "attn_norm": jnp.ones((L, d), pdt),
            "wq": dense(lk[0], L, d, h * dh, fan_in=d),
            "wk": dense(lk[1], L, d, kv * dh, fan_in=d),
            "wv": dense(lk[2], L, d, kv * dh, fan_in=d),
            "wo": dense(lk[3], L, h * dh, d, fan_in=h * dh),
            "mlp_norm": jnp.ones((L, d), pdt),
            "w_gate": dense(lk[4], L, d, f, fan_in=d),
            "w_up": dense(lk[5], L, d, f, fan_in=d),
            "w_down": dense(lk[6], L, f, d, fan_in=f),
        },
        "final_norm": jnp.ones((d,), pdt),
    }
    if not config.tie_embeddings:
        params["lm_head"] = dense(k_head, d, config.vocab_size, fan_in=d)
    return params


_QUANT_LEAVES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_llama_base(params: Params) -> Params:
    """int8-quantize the frozen base for a LoRA fine-tune.

    The seven stacked [L, din, dout] matmul weights get per-(layer,
    output-channel) scales; ``lm_head`` a per-column scale; embeddings
    and norms stay in their float dtype (gathered/elementwise — no MXU
    matmul to fuse a dequant into).  Halves the bf16 footprint again:
    Llama-3-8B base ≈ 8 GB, fitting a 16 GB v5e chip with adapters +
    Adam moments to spare (BASELINE.json config #4 at literal scale).
    Use with :func:`make_lora_train_step` only — the base must stay
    frozen (int8 leaves carry no gradient).
    """
    from rayfed_tpu.models.quant import quantize_int8

    out = dict(params)
    out["layers"] = {
        k: (
            quantize_int8(v, channel_axis=-1, batch_axes=(0,))
            if k in _QUANT_LEAVES
            else v
        )
        for k, v in params["layers"].items()
    }
    if "lm_head" in params:
        out["lm_head"] = quantize_int8(params["lm_head"], channel_axis=-1)
    return out


def init_llama_int8(key: jax.Array, config: LlamaConfig) -> Params:
    """Random int8-quantized base, built WITHOUT a full-precision pass.

    Each matmul weight is generated directly as int8 (uniform in
    [-127, 127]) with a fan-in-scaled per-channel dequant scale, so peak
    memory during init is the int8 tree itself — at 8B the bf16
    intermediate that ``init_llama`` + :func:`quantize_llama_base` would
    build (~16 GB) never exists.  For benches and scaffolding; real runs
    load quantized checkpoints.
    """
    from rayfed_tpu.models.quant import QTensor

    d = config.hidden_size
    dh = config.head_dim
    h, kv = config.num_heads, config.num_kv_heads
    f = config.intermediate_size
    L = config.num_layers
    pdt = config.param_dtype
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def qdense(key, *shape, fan_in):
        q = jax.random.randint(key, shape, -127, 128, dtype=jnp.int8)
        # E[q^2] ≈ 127^2/3 ⇒ scale for unit-ish activations: 1/(73·√fan_in).
        scale_shape = (shape[0], *([1] * (len(shape) - 2)), shape[-1])
        scale = jnp.full(scale_shape, (fan_in**-0.5) / 73.0, jnp.float32)
        return QTensor(q=q, scale=scale)

    lk = jax.random.split(k_layers, 7)
    params: Params = {
        "embed": (
            jax.random.normal(k_embed, (config.vocab_size, d), pdt) * 0.02 * d**0.5
        ),
        "layers": {
            "attn_norm": jnp.ones((L, d), pdt),
            "wq": qdense(lk[0], L, d, h * dh, fan_in=d),
            "wk": qdense(lk[1], L, d, kv * dh, fan_in=d),
            "wv": qdense(lk[2], L, d, kv * dh, fan_in=d),
            "wo": qdense(lk[3], L, h * dh, d, fan_in=h * dh),
            "mlp_norm": jnp.ones((L, d), pdt),
            "w_gate": qdense(lk[4], L, d, f, fan_in=d),
            "w_up": qdense(lk[5], L, d, f, fan_in=d),
            "w_down": qdense(lk[6], L, f, d, fan_in=f),
        },
        "final_norm": jnp.ones((d,), pdt),
    }
    if not config.tie_embeddings:
        head = jax.random.randint(
            k_head, (d, config.vocab_size), -127, 128, dtype=jnp.int8
        )
        from rayfed_tpu.models.quant import QTensor as _QT

        params["lm_head"] = _QT(
            q=head,
            scale=jnp.full((1, config.vocab_size), (d**-0.5) / 73.0, jnp.float32),
        )
    return params


def _rms_norm(x, scale, eps):
    xf = x.astype(jnp.float32)
    norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (norm * scale.astype(jnp.float32)).astype(x.dtype)


def rope_tables(positions: jax.Array, head_dim: int, theta: float):
    """cos/sin tables [T, head_dim/2] for the given absolute positions."""
    freqs = 1.0 / theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate pairs (x[..., ::2], x[..., 1::2]); x: [B, T, H, Dh]."""
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    c = cos[None, :, None, :].astype(x.dtype)
    s = sin[None, :, None, :].astype(x.dtype)
    r1 = x1 * c - x2 * s
    r2 = x2 * c + x1 * s
    return jnp.stack([r1, r2], axis=-1).reshape(x.shape)


def _linear(x, w, lora_entry, dtype):
    """x @ w with an optional LoRA low-rank bypass (x@A)@B · scale.

    ``w`` may be an int8 :class:`~rayfed_tpu.models.quant.QTensor` (frozen
    base in a LoRA fine-tune); quant.matmul keeps the weight-side dequant
    a pure fusable convert (scale applied to the output) so decode reads
    int8 bytes from HBM, not a materialized bf16 copy."""
    from rayfed_tpu.models.quant import matmul

    out = matmul(x, w, dtype)
    if lora_entry is not None:
        a = lora_entry["a"].astype(dtype)
        b = lora_entry["b"].astype(dtype)
        scale = jax.lax.stop_gradient(lora_entry["scale"]).astype(dtype)
        out = out + (x @ a) @ b * scale
    return out


def _no_lora(name):
    return None


def _qkv_proj(y, lp, config, b, t, lget=_no_lora):
    """Project + reshape + RoPE-ready q/k/v — shared by the training
    forward and the KV-cache decode step (keep their numerics in sync)."""
    h, kv, dh = config.num_heads, config.num_kv_heads, config.head_dim
    dtype = config.dtype
    q = _linear(y, lp["wq"], lget("wq"), dtype).reshape(b, t, h, dh)
    k = _linear(y, lp["wk"], lget("wk"), dtype).reshape(b, t, kv, dh)
    v = _linear(y, lp["wv"], lget("wv"), dtype).reshape(b, t, kv, dh)
    return q, k, v


def _attn_out(x, attn, lp, config, b, t, lget=_no_lora):
    flat = attn.reshape(b, t, config.num_heads * config.head_dim)
    return x + _linear(flat, lp["wo"], lget("wo"), config.dtype)


def _mlp_block(x, lp, config, lget=_no_lora):
    """RMSNorm + SwiGLU MLP residual — shared by training and decode."""
    dtype = config.dtype
    y = _rms_norm(x, lp["mlp_norm"], config.rms_eps)
    gate = jax.nn.silu(_linear(y, lp["w_gate"], lget("w_gate"), dtype))
    up = _linear(y, lp["w_up"], lget("w_up"), dtype)
    return x + _linear(gate * up, lp["w_down"], lget("w_down"), dtype)


def _layer_fwd(x, lp, config, cos, sin, attn_fn, b, t, lget=_no_lora,
               emit_kv=False):
    """One decoder layer (norm→qkv→RoPE→GQA attn→out→MLP) — the single
    implementation behind the training forward AND prefill, so their
    numerics cannot drift.  With ``emit_kv`` also returns the pre-repeat
    k/v (for KV-cache assembly)."""
    h, kv = config.num_heads, config.num_kv_heads
    y = _rms_norm(x, lp["attn_norm"], config.rms_eps)
    q, k, v = _qkv_proj(y, lp, config, b, t, lget)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    k_out, v_out = k, v
    if kv != h:  # GQA: repeat kv heads to match query heads
        reps = h // kv
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
    if config.sliding_window is not None:
        # Both dense and flash attn_fns accept window=; an attn_fn that
        # cannot honor it (ring/Ulysses wrappers) fails loudly here
        # rather than silently attending outside the band.
        attn = attn_fn(q, k, v, causal=True, window=config.sliding_window)
    else:
        attn = attn_fn(q, k, v, causal=True)
    x = _attn_out(x, attn, lp, config, b, t, lget)
    x = _mlp_block(x, lp, config, lget)
    return (x, (k_out, v_out)) if emit_kv else (x, None)


def _lm_head(x, params, config):
    """Final norm + vocabulary projection ([..., D] → [..., V] f32).

    bf16 MXU operands, f32 accumulation — a pure-f32 lm_head matmul runs
    at a fraction of bf16 throughput and the f32 accumulator already
    carries the precision the loss needs.
    """
    from rayfed_tpu.models.quant import split_output_scale

    x = _rms_norm(x, params["final_norm"], config.rms_eps)
    head = params.get("lm_head")
    out_scale = None
    if head is None:
        head = params["embed"].astype(config.dtype).T
    else:
        # Output-side scale keeps the weight feed a pure int8->bf16
        # convert (see quant.split_output_scale) — the lm_head is the
        # single largest weight read of a decode step.
        head, out_scale = split_output_scale(head, config.dtype)
    logits = jax.lax.dot_general(
        x.astype(config.dtype),
        head,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if out_scale is not None:
        logits = logits * out_scale.astype(logits.dtype)
    return logits


def apply_llama(
    params: Params,
    input_ids: jax.Array,
    config: LlamaConfig,
    *,
    lora: Optional[Params] = None,
    attn_fn: Callable = dot_product_attention,
    positions: Optional[jax.Array] = None,
) -> jax.Array:
    """Forward: [B, T] ids → [B, T, V] float32 logits (causal LM)."""
    b, t = input_ids.shape
    dtype = config.dtype
    h, kv, dh = config.num_heads, config.num_kv_heads, config.head_dim

    x = params["embed"].astype(dtype)[input_ids]
    if positions is None:
        positions = jnp.arange(t)
    cos, sin = rope_tables(positions, dh, config.rope_theta)

    lora_layers = (lora or {}).get("layers")
    # Scan xs need a leading layer dim on every leaf — hoist the scalar
    # LoRA scales out of the scanned tree into the closure.
    lora_scales = {}
    if lora_layers is not None:
        lora_scales = {k: v["scale"] for k, v in lora_layers.items()}
        lora_layers = {
            k: {"a": v["a"], "b": v["b"]} for k, v in lora_layers.items()
        }

    def layer_body(x, scanned):
        lp = scanned["w"]
        ll = scanned.get("lora")

        def lget(name):
            if ll is None or name not in ll:
                return None
            return {**ll[name], "scale": lora_scales[name]}

        return _layer_fwd(x, lp, config, cos, sin, attn_fn, b, t, lget)

    if config.remat:
        # Values are validated in LlamaConfig.__post_init__; the explicit
        # dispatch stays exhaustive so a future policy added to the
        # whitelist cannot silently fall through to the wrong one.
        if config.remat_policy is None:
            layer_body = jax.checkpoint(layer_body)
        elif config.remat_policy == "dots":
            layer_body = jax.checkpoint(
                layer_body,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            )
        else:  # pragma: no cover — unreachable past __post_init__
            raise AssertionError(config.remat_policy)

    scanned = {"w": params["layers"]}
    if lora_layers is not None:
        scanned["lora"] = lora_layers
    x, _ = jax.lax.scan(layer_body, x, scanned)

    return _lm_head(x, params, config)


# ---------------------------------------------------------------------------
# KV-cache decoding (autoregressive inference)
# ---------------------------------------------------------------------------


def _quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 over the trailing (head) dim: [..., Dh] →
    (int8 [..., Dh], f32 scale [..., 1]).  Zero vectors quantize to
    zeros (scale floor), so fresh cache slots stay exact."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale


def init_kv_cache(config: LlamaConfig, batch: int, max_len: int) -> Params:
    """Static-shape KV cache: ``k``/``v`` are [L, B, max_len, KV, Dh].

    Static shapes keep the decode step a single compiled XLA program —
    position advances by ``dynamic_update_slice`` writes plus a length
    mask, never a shape change.

    With ``config.kv_quant`` the k/v planes are int8 and per-(position,
    head) f32 scales ride alongside as ``k_scale``/``v_scale``
    [L, B, max_len, KV, 1] — 0.53× the bf16 cache bytes.
    """
    kvh, dh, L = config.num_kv_heads, config.head_dim, config.num_layers
    shape = (L, batch, max_len, kvh, dh)
    if config.kv_quant:
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(shape[:-1] + (1,), jnp.float32),
            "v_scale": jnp.zeros(shape[:-1] + (1,), jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, config.dtype),
        "v": jnp.zeros(shape, config.dtype),
    }


def init_rolling_kv_cache(config: LlamaConfig, batch: int) -> Params:
    """Ring-buffer cache of exactly ``sliding_window`` slots — decode
    memory stays O(W) for unbounded generation (pair with
    ``make_decode_step(config, rolling=True)``)."""
    if config.sliding_window is None:
        raise ValueError("a rolling cache requires config.sliding_window")
    return init_kv_cache(config, batch, config.sliding_window)


def roll_kv_cache(cache: Params, config: LlamaConfig, t0: int) -> Params:
    """Re-layout a (prefilled) linear cache into the rolling ring buffer.

    ``t0``: tokens already in the cache (prefill length).  Slot ``i`` of
    the ring receives the newest cached position congruent to ``i`` mod
    W; slots whose position would be negative (``t0 < W``) hold garbage
    that the rolling step's validity arithmetic masks out.
    """
    w = config.sliding_window
    if w is None:
        raise ValueError("roll_kv_cache requires config.sliding_window")
    max_len = cache["k"].shape[2]
    last = t0 - 1
    slots = jnp.arange(w)
    src = last - jnp.mod(last - slots, w)  # abs position for slot i
    src_idx = jnp.clip(src, 0, max_len - 1)
    return {
        name: jnp.take(plane, src_idx, axis=2)
        for name, plane in cache.items()
    }


@functools.lru_cache(maxsize=None)
def make_decode_step(config: LlamaConfig, rolling: bool = False):
    """One-token autoregressive step as a single jitted program.

    Returns ``step(params, cache, token_ids, pos) -> (cache, logits)``:
    ``token_ids`` is [B] (this position's token per sequence), ``pos`` a
    traced scalar position; ``logits`` is [B, V] float32 for the NEXT
    token.  The cache (donated) is updated in place in HBM.

    Numerics match :func:`apply_llama` at the same position (the layer
    math is shared via ``_qkv_proj``/``_attn_out``/``_mlp_block``): same
    RoPE tables, f32 softmax over the masked cache, bf16 MXU matmuls
    with f32 accumulation for the lm_head.  Cached per config (frozen
    dataclass) so repeat callers reuse the compiled program.

    ``rolling`` (requires ``config.sliding_window``): the cache is a
    ring buffer of exactly ``W`` slots (:func:`init_rolling_kv_cache`)
    — token ``pos`` writes slot ``pos % W``, and slot validity falls out
    of the ring arithmetic (a slot is live iff its absolute position is
    ≥ 0; the band and causality are automatic because every resident
    position lies in ``(pos − W, pos]``).  Memory stays O(W) for
    unbounded generation; k/v carry RoPE at their absolute positions,
    so scores need no relocation when slots are overwritten.
    """
    if rolling and config.sliding_window is None:
        raise ValueError("rolling=True requires config.sliding_window")
    h, kvh, dh = config.num_heads, config.num_kv_heads, config.head_dim
    dtype = config.dtype

    def step(params, cache, token_ids, pos):
        pos = jnp.asarray(pos)
        b = token_ids.shape[0]
        max_len = cache["k"].shape[2]
        x = params["embed"].astype(dtype)[token_ids][:, None, :]  # [B,1,D]
        cos, sin = rope_tables(pos[None], dh, config.rope_theta)
        positions = jnp.arange(max_len)
        if rolling:
            # The ring modulus IS the window; a linear cache passed here
            # by mistake (skipping roll_kv_cache) would silently widen
            # the attention window — reject it at trace time.
            if max_len != config.sliding_window:
                raise ValueError(
                    f"rolling decode needs a {config.sliding_window}-slot "
                    f"ring cache (init_rolling_kv_cache/roll_kv_cache), "
                    f"got {max_len} slots"
                )
            # Slot i holds absolute position pos − ((pos − i) mod W);
            # live iff that position is ≥ 0.
            write_pos = jnp.mod(pos, max_len)
            abs_pos = pos - jnp.mod(pos - positions, max_len)
            valid = abs_pos >= 0
        else:
            # Valid-length mask over the static cache: positions <= pos
            # (and, under sliding-window attention, within the band).
            write_pos = pos
            valid = positions <= pos  # [T]
            if config.sliding_window is not None:
                valid = valid & (positions > pos - config.sliding_window)

        def layer_body(x, scanned):
            lp = scanned["w"]
            k_cache = scanned["k"]  # [B, T, KV, Dh]
            v_cache = scanned["v"]

            y = _rms_norm(x, lp["attn_norm"], config.rms_eps)
            q, k, v = _qkv_proj(y, lp, config, b, 1)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            out_cache = {}
            if config.kv_quant:
                k_q, k_s = _quantize_kv(k)
                v_q, v_s = _quantize_kv(v)
                k_cache = jax.lax.dynamic_update_slice(
                    k_cache, k_q, (0, write_pos, 0, 0)
                )
                v_cache = jax.lax.dynamic_update_slice(
                    v_cache, v_q, (0, write_pos, 0, 0)
                )
                out_cache["k_scale"] = jax.lax.dynamic_update_slice(
                    scanned["k_scale"], k_s, (0, write_pos, 0, 0)
                )
                out_cache["v_scale"] = jax.lax.dynamic_update_slice(
                    scanned["v_scale"], v_s, (0, write_pos, 0, 0)
                )
            else:
                k_cache = jax.lax.dynamic_update_slice(
                    k_cache, k.astype(k_cache.dtype), (0, write_pos, 0, 0)
                )
                v_cache = jax.lax.dynamic_update_slice(
                    v_cache, v.astype(v_cache.dtype), (0, write_pos, 0, 0)
                )
            # GQA: group query heads over the shared kv head (g = H/KV).
            # Native-dtype (bf16) MXU operands with f32 accumulation —
            # casting the whole static cache to f32 would materialize
            # multi-MB copies per layer in the per-token hot loop.
            g = h // kvh
            qs = (q.reshape(b, h, dh) * dh**-0.5).astype(dtype)
            qs = qs.reshape(b, kvh, g, dh)
            s = jnp.einsum(
                "bngd,btnd->bngt", qs, k_cache.astype(dtype),
                preferred_element_type=jnp.float32,
            )
            if config.kv_quant:
                # The per-(position, head) k scale is constant over the
                # contracted head dim, so it factors out of the dot and
                # lands on the small [B, KV, g, T] score tensor — the
                # int8 cache plane is read raw, never dequantized in HBM.
                k_s_t = out_cache["k_scale"][..., 0].transpose(0, 2, 1)
                s = s * k_s_t[:, :, None, :]
            s = jnp.where(valid[None, None, None, :], s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            if config.kv_quant:
                # Same trick on the value side: fold the v scale into
                # the probabilities before the weighted sum.
                v_s_t = out_cache["v_scale"][..., 0].transpose(0, 2, 1)
                p = p * v_s_t[:, :, None, :]
            attn = jnp.einsum(
                "bngt,btnd->bngd", p.astype(dtype), v_cache.astype(dtype),
                preferred_element_type=jnp.float32,
            )  # [B, KV, g, Dh]
            attn = attn.reshape(b, 1, h, dh).astype(dtype)
            x = _attn_out(x, attn, lp, config, b, 1)
            x = _mlp_block(x, lp, config)
            out_cache["k"] = k_cache
            out_cache["v"] = v_cache
            return x, out_cache

        scanned = {"w": params["layers"], "k": cache["k"], "v": cache["v"]}
        if config.kv_quant:
            scanned["k_scale"] = cache["k_scale"]
            scanned["v_scale"] = cache["v_scale"]
        x, new_cache = jax.lax.scan(layer_body, x, scanned)

        return new_cache, _lm_head(x[:, 0, :], params, config)

    return jax.jit(step, donate_argnums=(1,))


def prefill(
    params: Params,
    config: LlamaConfig,
    prompt_ids: jax.Array,
    max_len: int,
    *,
    attn_fn: Callable = dot_product_attention,
) -> Tuple[Params, jax.Array]:
    """Process the whole prompt in ONE causal pass and return
    ``(cache, last_logits)`` ready for :func:`make_decode_step`.

    Same layer math as :func:`apply_llama` (shared helpers), but the
    scan also emits each layer's k/v, zero-padded into the static
    [L, B, max_len, KV, Dh] cache layout.  O(T) matmul width instead of
    T sequential single-token steps.
    """
    b, t0 = prompt_ids.shape
    if t0 > max_len:
        raise ValueError(f"prompt length {t0} exceeds cache max_len {max_len}")
    dtype = config.dtype
    h, kv, dh = config.num_heads, config.num_kv_heads, config.head_dim

    x = params["embed"].astype(dtype)[prompt_ids]
    cos, sin = rope_tables(jnp.arange(t0), dh, config.rope_theta)

    def layer_body(x, lp):
        x, (k_out, v_out) = _layer_fwd(
            x, lp, config, cos, sin, attn_fn, b, t0, emit_kv=True
        )
        pad = [(0, 0), (0, max_len - t0), (0, 0), (0, 0)]
        if config.kv_quant:
            # Same quantizer as the decode step, position by position —
            # a prefilled cache matches sequential decode's up to the
            # matmul-shape-dependent last-ulp of the projections
            # (dequantized agreement tested).
            k_q, k_s = _quantize_kv(k_out)
            v_q, v_s = _quantize_kv(v_out)
            return x, {
                "k": jnp.pad(k_q, pad),
                "v": jnp.pad(v_q, pad),
                "k_scale": jnp.pad(k_s, pad),
                "v_scale": jnp.pad(v_s, pad),
            }
        return x, {"k": jnp.pad(k_out, pad), "v": jnp.pad(v_out, pad)}

    x, cache = jax.lax.scan(layer_body, x, params["layers"])

    return cache, _lm_head(x[:, -1, :], params, config)


def generate(
    params: Params,
    config: LlamaConfig,
    prompt_ids: jax.Array,
    max_new_tokens: int,
    *,
    attn_fn: Callable = dot_product_attention,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Autoregressive decoding: [B, T0] prompt → [B, T0+max_new_tokens].

    One batched causal pass over the prompt (:func:`prefill`, pass
    ``attn_fn=flash_attention`` for long prompts — dense attention
    materializes the [B,H,T,T] score tensor), then one ``lax.scan`` of
    single-token steps through the KV cache.

    ``temperature=0`` (default) is greedy argmax.  With a positive
    temperature, samples from softmax(logits/temperature), optionally
    truncated to the ``top_k`` most likely tokens; ``key`` is then
    required.
    """
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) requires key=")
    if temperature == 0.0 and (key is not None or top_k is not None):
        # The mirror mistake of the check above: sampling args that would
        # be silently ignored under greedy decoding.
        raise ValueError(
            "key/top_k are sampling arguments — pass temperature > 0 "
            "(or drop them for greedy decoding)"
        )
    if top_k is not None and not 0 < top_k <= config.vocab_size:
        raise ValueError(
            f"top_k must be in [1, vocab_size={config.vocab_size}], got {top_k}"
        )
    b, t0 = prompt_ids.shape
    max_len = t0 + max_new_tokens
    cache, logits = prefill(params, config, prompt_ids, max_len, attn_fn=attn_fn)
    step = make_decode_step(config)
    keys = (
        jax.random.split(key, max_new_tokens)
        if temperature > 0.0
        else jnp.zeros((max_new_tokens, 2), jnp.uint32)
    )

    def pick(logits, k):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1)
        scaled = logits / temperature
        if top_k is not None:
            # Partial top-k, not a full vocab sort — this runs inside
            # the per-token decode loop.
            kth = jax.lax.top_k(scaled, top_k)[0][:, -1][:, None]
            scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
        return jax.random.categorical(k, scaled, axis=-1)

    def gen_body(carry, inputs):
        i, k = inputs
        cache, logits = carry
        token = pick(logits, k).astype(prompt_ids.dtype)
        cache, logits = step(params, cache, token, t0 + i)
        return (cache, logits), token

    (_, logits), tokens = jax.lax.scan(
        gen_body, (cache, logits), (jnp.arange(max_new_tokens), keys)
    )
    return jnp.concatenate([prompt_ids, tokens.T], axis=1)


def greedy_generate(
    params: Params,
    config: LlamaConfig,
    prompt_ids: jax.Array,
    max_new_tokens: int,
    *,
    attn_fn: Callable = dot_product_attention,
) -> jax.Array:
    """Greedy decoding (temperature-0 :func:`generate`)."""
    return generate(
        params, config, prompt_ids, max_new_tokens, attn_fn=attn_fn
    )


def lm_loss(logits: jax.Array, targets: jax.Array, mask=None) -> jax.Array:
    """Next-token cross entropy; ``targets``[i] is the label for pos i."""
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


# Partition rules (stacked layout: dim 0 is the layer axis — never shard).
PARTITION_RULES = (
    (r"layers/w[qkv]$", P(None, "fsdp", "tp")),
    (r"layers/wo$", P(None, "tp", "fsdp")),
    (r"layers/w_(gate|up)$", P(None, "fsdp", "tp")),
    (r"layers/w_down$", P(None, "tp", "fsdp")),
    (r"^embed$", P("tp", "fsdp")),
    (r"^lm_head$", P("fsdp", "tp")),
)


def _adam_update(params, grads, opt, lr, b1, b2, eps):
    """Adam step; arithmetic in float32 regardless of the storage dtype
    (params/moments may be bfloat16 — see ``LlamaConfig.param_dtype``)."""
    count, m, v = opt
    count = count + 1
    f32 = jnp.float32
    m = jax.tree_util.tree_map(
        lambda m_, g: (b1 * m_.astype(f32) + (1 - b1) * g.astype(f32)).astype(
            m_.dtype
        ),
        m,
        grads,
    )
    v = jax.tree_util.tree_map(
        lambda v_, g: (
            b2 * v_.astype(f32) + (1 - b2) * g.astype(f32) ** 2
        ).astype(v_.dtype),
        v,
        grads,
    )
    mhat_scale = 1.0 / (1 - b1**count)
    vhat_scale = 1.0 / (1 - b2**count)
    params = jax.tree_util.tree_map(
        lambda p, m_, v_: (
            p.astype(f32)
            - lr
            * (m_.astype(f32) * mhat_scale)
            / (jnp.sqrt(v_.astype(f32) * vhat_scale) + eps)
        ).astype(p.dtype),
        params,
        m,
        v,
    )
    return params, (count, m, v)


def make_lora_train_step(
    config: LlamaConfig,
    lr: float = 1e-4,
    *,
    attn_fn: Callable = dot_product_attention,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    donate: bool = False,
):
    """Adam train step over **LoRA params only** (base weights frozen).

    Signature: (lora, opt, base_params, ids) → (lora, opt, loss); the
    next-token targets are ``ids`` shifted left.  ``opt`` = (step, m, v)
    from :func:`init_adam`.

    ``donate`` is opt-in: in a federated fine-tune the incoming adapters
    are also serialized for cross-party pushes, and donation would
    delete those buffers out from under the transport.
    """

    def loss_fn(lora, base_params, ids):
        logits = apply_llama(base_params, ids, config, lora=lora, attn_fn=attn_fn)
        return lm_loss(logits[:, :-1], ids[:, 1:])

    def step_fn(lora, opt, base_params, ids):
        loss, grads = jax.value_and_grad(loss_fn)(lora, base_params, ids)
        lora, opt = _adam_update(lora, grads, opt, lr, b1, b2, eps)
        return lora, opt, loss

    return jax.jit(step_fn, donate_argnums=(0, 1) if donate else ())


def make_train_step(
    config: LlamaConfig,
    lr: float = 3e-4,
    *,
    attn_fn: Callable = dot_product_attention,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
):
    """Full-parameter Adam train step: (params, opt, ids) → (params, opt, loss).

    Params and both Adam moments are donated — the step runs in place in
    HBM, which is what lets the whole optimizer state stay device-resident
    between steps (no host round-trips in the training loop).
    """

    def loss_fn(params, ids):
        logits = apply_llama(params, ids, config, attn_fn=attn_fn)
        return lm_loss(logits[:, :-1], ids[:, 1:])

    def step_fn(params, opt, ids):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids)
        params, opt = _adam_update(params, grads, opt, lr, b1, b2, eps)
        return params, opt, loss

    return jax.jit(step_fn, donate_argnums=(0, 1))


def make_train_loop(
    config: LlamaConfig,
    num_steps: int,
    lr: float = 3e-4,
    *,
    attn_fn: Callable = dot_product_attention,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
):
    """N full-param Adam steps in ONE compiled program (lax.scan).

    (params, opt, ids) → (params, opt, losses[num_steps]).  One dispatch
    covers all N steps — on hosts where the accelerator sits behind a
    high-latency link, per-call dispatch would otherwise dominate and
    make wall-clock throughput unmeasurable.
    """

    def loss_fn(params, ids):
        logits = apply_llama(params, ids, config, attn_fn=attn_fn)
        return lm_loss(logits[:, :-1], ids[:, 1:])

    def run(params, opt, ids):
        def body(carry, _):
            params, opt = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, ids)
            params, opt = _adam_update(params, grads, opt, lr, b1, b2, eps)
            return (params, opt), loss

        (params, opt), losses = jax.lax.scan(
            body, (params, opt), None, length=num_steps
        )
        return params, opt, losses

    return jax.jit(run, donate_argnums=(0, 1))


def make_lora_train_loop(
    config: LlamaConfig,
    num_steps: int,
    lr: float = 1e-4,
    *,
    attn_fn: Callable = dot_product_attention,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
):
    """N LoRA Adam steps in ONE compiled program (lax.scan).

    (lora, opt, base_params, ids) → (lora, opt, losses[num_steps]); base
    stays frozen (may be int8-quantized, see :func:`quantize_llama_base`).
    Same one-dispatch rationale as :func:`make_train_loop`.
    """

    def loss_fn(lora, base_params, ids):
        logits = apply_llama(base_params, ids, config, lora=lora, attn_fn=attn_fn)
        return lm_loss(logits[:, :-1], ids[:, 1:])

    def run(lora, opt, base_params, ids):
        def body(carry, _):
            lora, opt = carry
            loss, grads = jax.value_and_grad(loss_fn)(lora, base_params, ids)
            lora, opt = _adam_update(lora, grads, opt, lr, b1, b2, eps)
            return (lora, opt), loss

        (lora, opt), losses = jax.lax.scan(
            body, (lora, opt), None, length=num_steps
        )
        return lora, opt, losses

    return jax.jit(run, donate_argnums=(0, 1))


def param_count(params: Params, *, exclude_embed: bool = False) -> int:
    """Total parameter count (optionally excluding the embedding table).

    Works on real arrays or ``jax.eval_shape`` abstract values — use
    ``param_count(jax.eval_shape(lambda: init_llama(k, cfg)))`` to count
    without allocating.
    """
    import math

    total = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        name = jax.tree_util.keystr(path)
        if exclude_embed and "embed" in name:
            continue
        total += math.prod(leaf.shape)
    return total


def init_adam(params: Params):
    """Adam state (step, m, v).

    ``v`` is float32 regardless of the param storage dtype: with
    b2=0.999 the 0.1% per-step EMA change is under half a bf16 ulp, so a
    bfloat16 second moment could grow but never decay (the cast back
    rounds to the unchanged value).  ``m`` follows the param dtype — its
    b1=0.9 EMA moves ~10% per step, far above bf16 rounding, and keeping
    it narrow is part of fitting 1B params + Adam on one 16 GB chip.
    """
    zeros = functools.partial(jax.tree_util.tree_map, jnp.zeros_like)
    zeros32 = functools.partial(
        jax.tree_util.tree_map, lambda p: jnp.zeros(p.shape, jnp.float32)
    )
    return (jnp.zeros((), jnp.int32), zeros(params), zeros32(params))
