"""LoRA adapters over arbitrary param pytrees.

Low-Rank Adaptation for the federated fine-tune workload (BASELINE.md
config #4): each party trains only the small A/B factors; FedAvg
aggregates adapters (kilobytes over DCN instead of the full model).

Generic over any pytree: ``init_lora`` matches leaves by path regex and
creates factors over the *last two* dims, treating leading dims (e.g. the
stacked layer axis of :mod:`rayfed_tpu.models.llama`) as batch.  The
compute path never materializes ``W + AB`` — consumers add the low-rank
bypass ``(x@A)@B·scale`` (see ``llama._linear``), which is both faster
and keeps the frozen weights donate-able.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: Sequence[str] = (r"w[qv]$",)  # regexes over '/'-joined paths
    init_scale: float = 0.01

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:  # pragma: no cover
            parts.append(str(p))
    return "/".join(parts)


def init_lora(key: jax.Array, params: Params, config: LoraConfig) -> Params:
    """Build a LoRA tree mirroring the subtrees of matched ≥2-D leaves.

    Returned tree has the same *container* structure as ``params`` but
    only matched leaves, each replaced by ``{"a", "b", "scale"}``.
    A is gaussian-init, B zero-init (adapter starts as identity).
    """
    from rayfed_tpu.models.quant import QTensor

    compiled = [re.compile(pat) for pat in config.targets]
    # QTensors are leaves here: the adapter mirrors the LOGICAL weight
    # (its int8 q + scale children must not split the path match).
    leaves = jax.tree_util.tree_leaves_with_path(
        params, is_leaf=lambda x: isinstance(x, QTensor)
    )
    out: Params = {}
    for path, leaf in leaves:
        path_s = _path_str(path)
        if leaf.ndim < 2 or not any(c.search(path_s) for c in compiled):
            continue
        key, sub = jax.random.split(key)
        lead = leaf.shape[:-2]
        d_in, d_out = leaf.shape[-2], leaf.shape[-1]
        entry = {
            "a": jax.random.normal(sub, (*lead, d_in, config.rank), jnp.float32)
            * config.init_scale,
            "b": jnp.zeros((*lead, config.rank, d_out), jnp.float32),
            "scale": jnp.asarray(config.scaling, jnp.float32),
        }
        # Insert at the same nested position.
        node = out
        keys = path_s.split("/")
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = entry
    return out


def lora_delta(entry: Params) -> jax.Array:
    """Materialized AB·scale delta (for merging only, not the hot path)."""
    return (
        jnp.einsum("...ir,...ro->...io", entry["a"], entry["b"]) * entry["scale"]
    )


def merge_lora(params: Params, lora: Params) -> Params:
    """Fold adapters into the base weights: W ← W + AB·scale."""

    def _merge(base_node, lora_node):
        if isinstance(lora_node, dict) and set(lora_node) == {"a", "b", "scale"}:
            from rayfed_tpu.models.quant import QTensor

            if isinstance(base_node, QTensor):
                raise TypeError(
                    "cannot merge LoRA into an int8-quantized base; "
                    "dequantize first (QTensor.dequantize) or keep the "
                    "adapter separate"
                )
            return (base_node + lora_delta(lora_node)).astype(base_node.dtype)
        if isinstance(lora_node, dict):
            return {
                k: _merge(base_node[k], lora_node[k]) if k in lora_node else base_node[k]
                for k in base_node
            }
        return base_node

    return _merge(params, lora)


def num_lora_params(lora: Params) -> int:
    sizes = [
        x.size
        for path, x in jax.tree_util.tree_leaves_with_path(lora)
        if not _path_str(path).endswith("scale")
    ]
    return int(sum(sizes))
