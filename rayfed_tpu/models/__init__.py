"""Model families for federated workloads (pure-JAX, pytree params).

The reference ships no models (SURVEY §1: "no model layer") — users bring
TF/Torch code inside Ray tasks.  Here the model zoo is part of the
framework, built TPU-first: functional ``init``/``apply`` pairs over
plain param pytrees (easy to shard with
:func:`rayfed_tpu.parallel.sharding.shard_params_by_rules`, easy to
FedAvg by tree-mapping), bfloat16-friendly compute, MXU-shaped matmuls,
and pluggable attention (dense / pallas flash / ring / Ulysses).

Families cover the BASELINE.md configs:

- :mod:`logistic`  — MNIST logistic regression + MLP (config #2)
- :mod:`resnet`    — ResNet-18 for CIFAR-10 (config #3)
- :mod:`bert`      — BERT-style encoder, split-FL friendly (config #5)
- :mod:`llama`     — Llama-3-style decoder (RoPE/GQA/SwiGLU) (config #4)
- :mod:`lora`      — LoRA adapters over any linear param (config #4)
- :mod:`moe`       — mixture-of-experts layer, expert-parallel over ep
- :mod:`quant`     — int8 weight-only quantization (frozen bases, KV)
- :mod:`hf`        — Hugging Face Llama checkpoint conversion
  (logit-parity verified against ``transformers``)
"""

from rayfed_tpu.models import bert, hf, llama, logistic, lora, moe, quant, resnet

__all__ = [
    "logistic", "resnet", "bert", "llama", "lora", "moe", "quant", "hf",
]
