"""Weight-only int8 quantization for frozen base models.

TPU-first rationale: a LoRA fine-tune never updates the base weights, so
they can live in HBM as int8 with a per-output-channel scale — halving
bf16's footprint again and fitting Llama-3-8B (+adapters +Adam moments)
on one 16 GB v5e chip.  Dequantization (``int8 → bf16 × scale``) fuses
into the consuming matmul under XLA, so the MXU still sees bf16 operands;
there is no custom kernel to maintain.

Absent from the reference (it ships no model layer at all, SURVEY §1);
this supports BASELINE.json config #4 at its literal 8B scale.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QTensor:
    """An int8 weight plus its per-output-channel dequantization scale.

    ``q``: int8, the stored weight.  ``scale``: broadcastable to ``q``'s
    shape (per-channel: size-1 on every axis except the channel axis).
    Logical value: ``q * scale``.
    """

    q: jax.Array
    scale: jax.Array

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):  # storage dtype; dequantized dtype is the caller's
        return self.q.dtype

    @property
    def ndim(self) -> int:
        return self.q.ndim

    @property
    def nbytes(self) -> int:
        return self.q.size * 1 + self.scale.size * self.scale.dtype.itemsize

    def dequantize(self, dtype=jnp.float32) -> jax.Array:
        return self.q.astype(dtype) * self.scale.astype(dtype)


def quantize_int8(
    w: jax.Array, *, channel_axis: int = -1, batch_axes: tuple = ()
) -> QTensor:
    """Symmetric per-channel int8 quantization.

    ``channel_axis``: the output-feature axis whose scale is kept
    per-channel.  ``batch_axes``: additional axes that keep their own
    scale (e.g. the stacked-layer axis 0 of a scanned [L, din, dout]
    weight — without it all layers would share one scale).  Max-abs
    scaling: values map onto [-127, 127] with zero preserved exactly.
    """
    keep = {channel_axis % w.ndim} | {a % w.ndim for a in batch_axes}
    axes = tuple(i for i in range(w.ndim) if i not in keep)
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return QTensor(q=q.astype(jnp.int8), scale=scale.astype(jnp.float32))


def as_weight(w: Any, dtype) -> jax.Array:
    """Materialize a weight leaf for a matmul: dequantize QTensors, cast
    everything else.  The dequant fuses into the consuming dot under jit."""
    if isinstance(w, QTensor):
        return w.dequantize(dtype)
    return w.astype(dtype)


def _scale_is_per_last_axis(scale: jax.Array) -> bool:
    return all(d == 1 for d in scale.shape[:-1])


def split_output_scale(w: Any, dtype):
    """``(operand, out_scale)`` for a matmul contracting ``w``'s leading axes.

    For a :class:`QTensor` whose scale is constant along every contracted
    axis (per-OUTPUT-channel: size-1 everywhere but the last axis), the
    dequantization commutes with the contraction — return the raw int8
    weight as a pure-convert operand plus the [D_out] scale to apply to
    the matmul OUTPUT.  Callers that build their own dot (e.g. a
    ``preferred_element_type`` lm_head) share this invariant instead of
    re-deriving it.  Anything else returns ``(dense weight, None)``.
    """
    if isinstance(w, QTensor) and _scale_is_per_last_axis(w.scale):
        # reshape(-1): also covers a 0-d per-tensor scale (QTensor's
        # contract only demands broadcastability), which becomes a
        # shape-(1,) output scale.
        return w.q.astype(dtype), w.scale.reshape(-1)
    return as_weight(w, dtype), None


def matmul(x: jax.Array, w: Any, dtype) -> jax.Array:
    """``x @ w`` with the int8 path arranged for memory-bound decode.

    For a per-output-channel :class:`QTensor` the scale moves to the
    OUTPUT: ``(x @ q.astype(dtype)) * scale`` — algebraically identical
    to ``x @ (q * scale)`` (the scale is constant along the contracted
    axis), but the weight-side op becomes a *pure convert* that XLA
    fuses into the dot's operand feed instead of a convert+broadcast-
    multiply it tends to materialize as a full dequantized copy in HBM.
    At decode (GEMV, bandwidth-bound on weight reads) that
    materialization costs ~2.5 bytes/param of traffic where the int8
    read should cost 1 — the difference between int8 decode running at
    int8 bandwidth and running *slower* than bf16.  Other scale layouts
    fall back to explicit dequantization.
    """
    operand, out_scale = split_output_scale(w, dtype)
    out = x @ operand
    if out_scale is not None:
        out = out * out_scale.astype(dtype)
    return out


def is_quantized(w: Any) -> bool:
    return isinstance(w, QTensor)


def quantize_tree(
    params: Any,
    *,
    predicate: Optional[Callable[[str, jax.Array], bool]] = None,
    channel_axis: int = -1,
) -> Any:
    """Quantize matching array leaves of a param pytree to :class:`QTensor`.

    ``predicate(path_str, leaf) -> bool`` selects leaves (default: every
    float leaf with ndim >= 2 — matmul weights; norms/biases stay as-is).
    """

    def _default(_path: str, leaf: jax.Array) -> bool:
        return leaf.ndim >= 2 and jnp.issubdtype(leaf.dtype, jnp.floating)

    pred = predicate or _default

    def _maybe(path, leaf):
        path_str = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path
        )
        if isinstance(leaf, jax.Array) and pred(path_str, leaf):
            return quantize_int8(leaf, channel_axis=channel_axis)
        return leaf

    return jax.tree_util.tree_map_with_path(_maybe, params)


def tree_nbytes(params: Any) -> int:
    """Storage bytes of a (possibly quantized) param tree."""
    return sum(
        leaf.nbytes
        for leaf in jax.tree_util.tree_leaves(params)
        if hasattr(leaf, "nbytes")
    )
