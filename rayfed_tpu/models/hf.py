"""Hugging Face Llama checkpoint interop.

``from_hf_llama`` converts a ``transformers`` Llama model (or its
state dict) into this framework's param tree + :class:`LlamaConfig`,
so real pretrained weights drop into every path here — training, LoRA,
int8 quantization, KV-cache decode, and the federated exchanges.

Two convention differences are handled explicitly:

- **Weight orientation**: torch ``nn.Linear`` stores ``[out, in]``;
  this framework right-multiplies ``x @ W`` with ``[in, out]`` — every
  projection transposes.
- **RoPE layout**: HF rotates half-split pairs ``(j, j+Dh/2)``
  (``rotate_half``); this framework rotates interleaved pairs
  ``(2j, 2j+1)``.  The two are equivalent up to a static permutation of
  each head's output channels, applied here to ``wq``/``wk`` — after it
  the *logits are identical*, verified against ``transformers`` in
  ``tests/test_hf_interop.py``.

Logit parity is exact (f32 tolerance); nothing of the runtime imports
torch — the conversion is a one-time boundary step.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from rayfed_tpu.models.llama import LlamaConfig

Params = Dict[str, Any]


def _np(x) -> np.ndarray:
    """torch tensor / array-like → float32 numpy (host)."""
    if hasattr(x, "detach"):  # torch tensor, no torch import needed
        x = x.detach().cpu().float().numpy()
    return np.asarray(x, dtype=np.float32)


def _rope_perm(head_dim: int) -> np.ndarray:
    """Channel permutation taking HF's half-split RoPE layout to this
    framework's interleaved layout: out[2j] = j, out[2j+1] = j + Dh/2."""
    half = head_dim // 2
    perm = np.empty(head_dim, dtype=np.int64)
    perm[0::2] = np.arange(half)
    perm[1::2] = np.arange(half) + half
    return perm


def _permute_heads(w: np.ndarray, num_heads: int, head_dim: int) -> np.ndarray:
    """Apply the RoPE channel permutation per head on the out axis of a
    transposed projection ``[in, H·Dh]``."""
    d_in = w.shape[0]
    w = w.reshape(d_in, num_heads, head_dim)
    return w[:, :, _rope_perm(head_dim)].reshape(d_in, num_heads * head_dim)


def config_from_hf(hf_config) -> LlamaConfig:
    """Map a ``transformers.LlamaConfig`` onto :class:`LlamaConfig`.

    Features this framework does not implement are rejected loudly —
    silently dropping them would pass the shape audit and then diverge
    from ``transformers`` at every position.
    """
    if getattr(hf_config, "rope_scaling", None):
        raise NotImplementedError(
            "rope_scaling (Llama-3.1+ long-context scaling) is not "
            "implemented by rayfed_tpu.models.llama.rope_tables — "
            "convert a checkpoint without it or extend rope_tables first"
        )
    if getattr(hf_config, "attention_bias", False) or getattr(
        hf_config, "mlp_bias", False
    ):
        raise NotImplementedError(
            "attention_bias/mlp_bias checkpoints are not supported "
            "(this framework's Llama projections are bias-free)"
        )
    implied = hf_config.hidden_size // hf_config.num_attention_heads
    explicit = getattr(hf_config, "head_dim", None)
    if explicit is not None and explicit != implied:
        raise NotImplementedError(
            f"explicit head_dim={explicit} != hidden_size//num_heads="
            f"{implied}: this framework derives head_dim from the config"
        )
    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(
            hf_config, "num_key_value_heads", hf_config.num_attention_heads
        ),
        intermediate_size=hf_config.intermediate_size,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        rms_eps=float(hf_config.rms_norm_eps),
        max_seq_len=int(hf_config.max_position_embeddings),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
        # Mistral-style band (None on plain Llama configs) — supported
        # natively, so map rather than reject.
        sliding_window=getattr(hf_config, "sliding_window", None),
        dtype=jnp.float32,
        param_dtype=jnp.float32,
    )


def from_hf_llama(
    model_or_state: Any, config: Optional[LlamaConfig] = None
) -> Tuple[Params, LlamaConfig]:
    """Convert an HF Llama (model or state dict) → ``(params, config)``.

    ``model_or_state``: a ``transformers`` ``LlamaForCausalLM`` (config
    derived automatically) or its ``state_dict()`` (pass ``config``).
    Returned params are float32 numpy-backed jnp arrays in this
    framework's stacked-[L, ...] layout; cast or
    :func:`~rayfed_tpu.models.llama.quantize_llama_base` afterwards as
    needed.
    """
    if hasattr(model_or_state, "state_dict"):
        state = model_or_state.state_dict()
        if config is None:
            config = config_from_hf(model_or_state.config)
    else:
        state = dict(model_or_state)
        if config is None:
            raise ValueError("pass config= when converting a raw state dict")

    d, dh = config.hidden_size, config.head_dim
    h, kvh, L = config.num_heads, config.num_kv_heads, config.num_layers

    def get(name: str) -> np.ndarray:
        if name not in state:
            raise KeyError(
                f"HF checkpoint is missing {name!r} — not a Llama-family "
                f"state dict?"
            )
        return _np(state[name])

    def proj(name: str) -> np.ndarray:
        return get(name).T  # [out, in] -> [in, out]

    layers: Dict[str, list] = {
        k: []
        for k in (
            "attn_norm", "wq", "wk", "wv", "wo",
            "mlp_norm", "w_gate", "w_up", "w_down",
        )
    }
    for i in range(L):
        p = f"model.layers.{i}."
        layers["attn_norm"].append(get(p + "input_layernorm.weight"))
        layers["wq"].append(
            _permute_heads(proj(p + "self_attn.q_proj.weight"), h, dh)
        )
        layers["wk"].append(
            _permute_heads(proj(p + "self_attn.k_proj.weight"), kvh, dh)
        )
        layers["wv"].append(proj(p + "self_attn.v_proj.weight"))
        layers["wo"].append(proj(p + "self_attn.o_proj.weight"))
        layers["mlp_norm"].append(get(p + "post_attention_layernorm.weight"))
        layers["w_gate"].append(proj(p + "mlp.gate_proj.weight"))
        layers["w_up"].append(proj(p + "mlp.up_proj.weight"))
        layers["w_down"].append(proj(p + "mlp.down_proj.weight"))

    params: Params = {
        "embed": jnp.asarray(get("model.embed_tokens.weight")),
        "layers": {
            k: jnp.asarray(np.stack(v)) for k, v in layers.items()
        },
        "final_norm": jnp.asarray(get("model.norm.weight")),
    }
    if not config.tie_embeddings:
        params["lm_head"] = jnp.asarray(proj("lm_head.weight"))

    # Shape audit before handing the tree to jit: a silent mismatch
    # (e.g. wrong num_kv_heads) would otherwise surface as an obscure
    # einsum error deep inside the forward.
    expect = {
        "embed": (config.vocab_size, d),
        "final_norm": (d,),
    }
    for name, shape in expect.items():
        if params[name].shape != shape:
            raise ValueError(
                f"{name}: got {params[name].shape}, expected {shape}"
            )
    if params["layers"]["wq"].shape != (L, d, h * dh):
        raise ValueError(
            f"wq: got {params['layers']['wq'].shape}, expected "
            f"{(L, d, h * dh)}"
        )
    if params["layers"]["wk"].shape != (L, d, kvh * dh):
        raise ValueError(
            f"wk: got {params['layers']['wk'].shape}, expected "
            f"{(L, d, kvh * dh)}"
        )
    return params, config
